"""Asyncio RPC: length-prefixed msgpack frames over unix/tcp sockets.

The control-plane transport for the whole runtime — the role gRPC plays in
the reference (reference: src/ray/rpc/grpc_server.h, client_call.h). Design
differences, deliberately: one tiny symmetric protocol instead of per-service
protobuf schemas; connections are bidirectional (either side may issue
requests over an established connection), which removes the server→client
callback channels the reference needs (pubsub long-polling, owner RPCs).

Frame:   [u32 little-endian length][msgpack payload]
Payload: [type, seq, method, kwargs]          type: 0=request 1=response
         [1, seq, ok, result_or_error]              2=notify (no response)
Large binary values ride inside msgpack bin fields; bulk object payloads
never transit this layer (they live in the shm store / object transfer path).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import socket
import traceback
from typing import Any, Awaitable, Callable, Dict, Optional

import msgpack

logger = logging.getLogger(__name__)

REQUEST = 0
RESPONSE = 1
NOTIFY = 2
PARTIAL = 3     # [3, seq, idx, ok, payload] — streamed per-item response

_MAX_FRAME = 1 << 31
_EAGER_FLUSH_BYTES = 1 << 20    # frames this large skip the per-turn coalesce


class RpcError(Exception):
    """Remote handler raised; carries the remote traceback text."""

    def __init__(self, kind: str, message: str, remote_tb: str = ""):
        super().__init__(f"{kind}: {message}")
        self.kind = kind
        self.message = message
        self.remote_tb = remote_tb


_CHAOS_SPEC = None


def _maybe_inject_failure(method: str):
    """RPC chaos for fault-injection tests (reference: RpcFailureManager
    src/ray/rpc/rpc_chaos.cc:35 + RAY_testing_rpc_failure). Spec via env
    RAY_TPU_TESTING_RPC_FAILURE="method=prob,method2=prob"."""
    global _CHAOS_SPEC
    if _CHAOS_SPEC is None:
        import os
        spec = {}
        raw = os.environ.get("RAY_TPU_TESTING_RPC_FAILURE", "")
        for part in raw.split(","):
            if "=" in part:
                m, p = part.split("=", 1)
                try:
                    spec[m.strip()] = float(p)
                except ValueError:
                    pass
        _CHAOS_SPEC = spec
    prob = _CHAOS_SPEC.get(method)
    if prob:
        import random
        if random.random() < prob:
            raise RpcError("ChaosInjected",
                           f"injected chaos failure for {method!r}")


class ConnectionLost(Exception):
    pass


def _pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def _unpack(data) -> Any:
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


class Connection:
    """One bidirectional framed connection. Both peers can call/notify."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 handlers: Optional[Dict[str, Callable]] = None, name: str = "?"):
        self.reader = reader
        self.writer = writer
        self.handlers = handlers if handlers is not None else {}
        self.name = name
        self._seq = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._part_handlers: Dict[int, Callable] = {}
        self._out: list = []          # frames awaiting the per-turn flush
        self._closed = False
        self._drainer: Optional[asyncio.Task] = None
        self._task: Optional[asyncio.Task] = None
        self._dispatch_tasks: set = set()
        self.on_close: Optional[Callable[["Connection"], None]] = None
        # opaque slot for servers to stash peer identity (node id, worker id)
        self.peer_info: Dict[str, Any] = {}

    def start(self):
        self._task = asyncio.ensure_future(self._read_loop())
        return self

    @property
    def closed(self) -> bool:
        return self._closed

    async def _read_loop(self):
        try:
            while True:
                hdr = await self.reader.readexactly(4)
                n = int.from_bytes(hdr, "little")
                if n > _MAX_FRAME:
                    raise ConnectionLost(f"frame too large: {n}")
                body = await self.reader.readexactly(n)
                msg = _unpack(body)
                mtype = msg[0]
                if mtype == REQUEST or mtype == NOTIFY:
                    self._dispatch_msg(msg)
                elif mtype == RESPONSE:
                    _, seq, ok, payload = msg
                    self._part_handlers.pop(seq, None)
                    fut = self._pending.pop(seq, None)
                    if fut is not None and not fut.done():
                        if ok:
                            fut.set_result(payload)
                        else:
                            fut.set_exception(RpcError(*payload))
                elif mtype == PARTIAL:
                    _, seq, idx, ok, payload = msg
                    h = self._part_handlers.get(seq)
                    if h is not None:
                        try:
                            h(idx, ok, payload)
                        except Exception:
                            logger.exception("partial handler failed")
        except (asyncio.IncompleteReadError, ConnectionResetError,
                ConnectionLost, BrokenPipeError, OSError):
            pass
        except Exception:
            logger.exception("rpc read loop error on %s", self.name)
        finally:
            await self._shutdown()

    async def _shutdown(self):
        if self._closed:
            return
        self._flush_out()      # last frames (e.g. a final error response)
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(f"connection {self.name} lost"))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close is not None:
            try:
                cb = self.on_close
                self.on_close = None
                res = cb(self)
                if asyncio.iscoroutine(res):
                    await res
            except Exception:
                logger.exception("on_close callback failed for %s", self.name)

    def _dispatch_msg(self, msg):
        """Run a request/notify. Sync handlers and Future-returning handlers
        complete without spawning a task (the hot actor-call path); only
        true coroutines get one (reference keeps its hot path allocation-
        free the same way, src/ray/rpc/grpc_server.h ServerCall reuse)."""
        mtype, seq, method, kwargs = msg
        handler = self.handlers.get(method)
        if handler is None:
            if mtype == REQUEST:
                self._respond(seq, False, ("NotImplementedError",
                                           f"no handler {method!r}", ""))
            return
        if getattr(handler, "streaming", False) and mtype == REQUEST:
            # streaming handler: receives its seq and answers with
            # send_partial(...) + send_final(...) itself
            try:
                handler(self, seq, **kwargs)
            except Exception as e:
                self._handler_error(REQUEST, seq, method, e)
            return
        try:
            result = handler(self, **kwargs)
        except Exception as e:
            self._handler_error(mtype, seq, method, e)
            return
        if isinstance(result, asyncio.Future):
            if mtype == REQUEST:
                result.add_done_callback(
                    lambda f, s=seq, m=method: self._finish_request(s, m, f))
            return
        if asyncio.iscoroutine(result) or isinstance(result, Awaitable):
            t = asyncio.ensure_future(
                self._dispatch_async(mtype, seq, method, result))
            self._dispatch_tasks.add(t)
            t.add_done_callback(self._dispatch_tasks.discard)
            return
        if mtype == REQUEST:
            self._respond(seq, True, result)

    def _finish_request(self, seq, method, fut: asyncio.Future):
        if fut.cancelled():
            self._handler_error(REQUEST, seq, method,
                                asyncio.CancelledError("cancelled"))
            return
        exc = fut.exception()
        if exc is not None:
            self._handler_error(REQUEST, seq, method, exc)
        else:
            self._respond(seq, True, fut.result())

    async def _dispatch_async(self, mtype, seq, method, coro):
        try:
            result = await coro
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._handler_error(mtype, seq, method, e)
            return
        if mtype == REQUEST:
            self._respond(seq, True, result)

    def _handler_error(self, mtype, seq, method, e: BaseException):
        if mtype == REQUEST:
            try:
                self._respond(seq, False, (type(e).__name__, str(e),
                                           traceback.format_exc()))
            except (ConnectionLost, ConnectionError):
                pass
        else:
            logger.error("notify handler %s failed: %s", method, e)

    def _respond(self, seq, ok, payload):
        try:
            self._send_nowait([RESPONSE, seq, ok, payload])
        except (ConnectionLost, ConnectionError):
            pass   # peer gone; response undeliverable

    def _send_nowait(self, obj):
        """Serialize and queue for the next loop-iteration flush: every
        frame produced in one event-loop turn (pipelined requests, a
        burst of PARTIAL acks) leaves in ONE writelines/syscall. All
        sends happen on the event-loop thread, so frames never
        interleave. TCP backpressure: async senders await maybe_drain();
        a background drainer backstops fire-and-forget sends (round-2's
        drain()-per-message was the 0.1x pipelined-path bottleneck)."""
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        data = _pack(obj)
        out = self._out
        out.append(len(data).to_bytes(4, "little"))
        out.append(data)
        if len(data) >= _EAGER_FLUSH_BYTES:
            # bulk frame (object transfer chunk, big inline value): hand
            # it to the transport NOW so the kernel overlaps the send with
            # the rest of this loop turn instead of buffering megabytes
            # behind a call_soon
            self._flush_out()
            return
        if len(out) == 2:       # first frame this turn: schedule the flush
            asyncio.get_event_loop().call_soon(self._flush_out)

    def _flush_out(self):
        out = self._out
        if not out or self._closed:
            out.clear()
            return
        self._out = []
        try:
            self.writer.writelines(out)
        except Exception:
            return
        if self._drainer is None:
            transport = self.writer.transport
            if transport is not None and \
                    transport.get_write_buffer_size() > (1 << 20):
                self._drainer = asyncio.ensure_future(self._drain_bg())

    async def _drain_bg(self):
        try:
            await self.writer.drain()
        except Exception:
            pass
        finally:
            self._drainer = None

    def over_highwater(self) -> bool:
        transport = self.writer.transport
        return transport is not None and \
            transport.get_write_buffer_size() > (1 << 20)

    async def maybe_drain(self):
        """Await real TCP backpressure when the write buffer is past the
        high-water mark — async senders call this so a slow peer throttles
        them instead of buffering without bound."""
        if self._out:
            self._flush_out()
        if self.over_highwater():
            try:
                await self.writer.drain()
            except (ConnectionResetError, BrokenPipeError, OSError):
                raise ConnectionLost(f"connection {self.name} lost")

    async def _send(self, obj):
        self._send_nowait(obj)
        await self.maybe_drain()

    async def call(self, method: str, timeout: Optional[float] = None, **kwargs) -> Any:
        fut = self.call_start_nowait(method, kwargs)
        await self.maybe_drain()
        if timeout is not None:
            return await asyncio.wait_for(fut, timeout)
        return await fut

    def call_start_nowait(self, method: str, kwargs) -> asyncio.Future:
        """Issue the request and return the response future — sync, so
        submission order is the caller's statement order."""
        _maybe_inject_failure(method)
        seq = next(self._seq)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[seq] = fut
        try:
            self._send_nowait([REQUEST, seq, method, kwargs])
        except BaseException:
            self._pending.pop(seq, None)
            fut.cancel()
            raise
        return fut

    async def call_start(self, method: str, **kwargs) -> asyncio.Future:
        return self.call_start_nowait(method, kwargs)

    def call_start_parts(self, method: str, kwargs,
                         on_part: Callable) -> asyncio.Future:
        """Batched request with streamed per-item responses: `on_part(idx,
        ok, payload)` fires as each item completes on the peer; the
        returned future resolves when the peer sends the final RESPONSE.
        One frame out, per-item acks back — a worker death mid-batch
        only loses the unacked items."""
        seq = next(self._seq)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[seq] = fut
        self._part_handlers[seq] = on_part
        try:
            self._send_nowait([REQUEST, seq, method, kwargs])
        except BaseException:
            self._pending.pop(seq, None)
            self._part_handlers.pop(seq, None)
            fut.cancel()
            raise
        return fut

    def send_partial(self, seq: int, idx: int, ok: bool, payload):
        try:
            self._send_nowait([PARTIAL, seq, idx, ok, payload])
        except (ConnectionLost, ConnectionError):
            pass

    def send_final(self, seq: int, payload):
        self._respond(seq, True, payload)

    async def notify(self, method: str, **kwargs):
        self._send_nowait([NOTIFY, 0, method, kwargs])
        await self.maybe_drain()

    async def close(self):
        me = asyncio.current_task()
        victims = [t for t in [self._task, self._drainer,
                               *self._dispatch_tasks]
                   if t is not None and t is not me and not t.done()]
        for t in victims:
            t.cancel()
        if victims:
            await asyncio.gather(*victims, return_exceptions=True)
        await self._shutdown()


def parse_address(addr: str):
    """'unix:/path' or 'tcp:host:port' -> (kind, ...)."""
    if addr.startswith("unix:"):
        return ("unix", addr[5:])
    if addr.startswith("tcp:"):
        host, port = addr[4:].rsplit(":", 1)
        return ("tcp", host, int(port))
    # bare host:port
    host, port = addr.rsplit(":", 1)
    return ("tcp", host, int(port))


class Server:
    """RPC server accepting unix and/or tcp connections with shared handlers."""

    def __init__(self, handlers: Dict[str, Callable], name: str = "server"):
        self.handlers = handlers
        self.name = name
        self._servers = []
        self.connections: set = set()
        self.on_connection: Optional[Callable[[Connection], None]] = None
        self.on_disconnect: Optional[Callable[[Connection], None]] = None

    async def _on_client(self, reader, writer):
        sock = writer.get_extra_info("socket")
        if sock is not None and sock.family != socket.AF_UNIX:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = Connection(reader, writer, self.handlers,
                          name=f"{self.name}-peer").start()
        self.connections.add(conn)

        def _closed(c):
            self.connections.discard(c)
            if self.on_disconnect is not None:
                self.on_disconnect(c)

        conn.on_close = _closed
        if self.on_connection is not None:
            self.on_connection(conn)

    async def listen_unix(self, path: str):
        if os.path.exists(path):
            os.unlink(path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        srv = await asyncio.start_unix_server(self._on_client, path=path)
        self._servers.append(srv)
        return f"unix:{path}"

    async def listen_tcp(self, host: str = "0.0.0.0", port: int = 0) -> str:
        srv = await asyncio.start_server(self._on_client, host=host, port=port,
                                         reuse_address=True)
        self._servers.append(srv)
        port = srv.sockets[0].getsockname()[1]
        return f"tcp:{_advertise_host(host)}:{port}"

    async def close(self):
        # connections BEFORE wait_closed: py3.12's Server.wait_closed()
        # waits for every live connection handler, so closing the
        # listening socket first deadlocks against our own still-open
        # peers (observed: driver shutdown hanging >5s after Data runs,
        # whose workers keep result-push conns to the driver open)
        for conn in list(self.connections):
            await conn.close()
        for srv in self._servers:
            srv.close()
            try:
                await asyncio.wait_for(srv.wait_closed(), 2.0)
            except asyncio.TimeoutError:
                pass


def _advertise_host(bind_host: str) -> str:
    if bind_host not in ("0.0.0.0", "::", ""):
        return bind_host
    return node_ip_address()


_cached_ip: Optional[str] = None


def node_ip_address() -> str:
    global _cached_ip
    if _cached_ip is None:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            # no traffic is sent; just picks the interface with a default route
            s.connect(("8.8.8.8", 80))
            _cached_ip = s.getsockname()[0]
        except OSError:
            _cached_ip = "127.0.0.1"
        finally:
            s.close()
    return _cached_ip


async def connect(addr: str, handlers: Optional[Dict[str, Callable]] = None,
                  name: str = "client", retries: int = 0,
                  retry_delay: float = 0.1) -> Connection:
    parsed = parse_address(addr)
    last_err: Optional[Exception] = None
    for attempt in range(retries + 1):
        try:
            if parsed[0] == "unix":
                reader, writer = await asyncio.open_unix_connection(parsed[1])
            else:
                reader, writer = await asyncio.open_connection(parsed[1], parsed[2])
                sock = writer.get_extra_info("socket")
                if sock is not None:
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return Connection(reader, writer, handlers, name=name).start()
        except (ConnectionRefusedError, FileNotFoundError, OSError) as e:
            last_err = e
            if attempt < retries:
                await asyncio.sleep(min(retry_delay * (1.5 ** attempt), 2.0))
    raise ConnectionError(f"cannot connect to {addr}: {last_err}")


class ConnectionPool:
    """Caches one Connection per address; reconnects lazily on loss."""

    def __init__(self, handlers: Optional[Dict[str, Callable]] = None,
                 name: str = "pool"):
        self.handlers = handlers or {}
        self.name = name
        self._conns: Dict[str, Connection] = {}
        self._locks: Dict[str, asyncio.Lock] = {}
        self._closing: set = set()

    async def get(self, addr: str) -> Connection:
        conn = self._conns.get(addr)
        if conn is not None and not conn.closed:
            return conn
        lock = self._locks.setdefault(addr, asyncio.Lock())
        async with lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.closed:
                return conn
            conn = await connect(addr, self.handlers,
                                 name=f"{self.name}->{addr}", retries=3)
            self._conns[addr] = conn
            return conn

    async def call(self, addr: str, method: str, **kwargs):
        conn = await self.get(addr)
        return await conn.call(method, **kwargs)

    def invalidate(self, addr: str):
        conn = self._conns.pop(addr, None)
        if conn is not None and not conn.closed:
            t = asyncio.ensure_future(conn.close())
            self._closing.add(t)
            t.add_done_callback(self._closing.discard)

    async def close(self):
        conns, self._conns = list(self._conns.values()), {}
        if conns:
            await asyncio.gather(*(c.close() for c in conns),
                                 return_exceptions=True)
        if self._closing:
            await asyncio.gather(*list(self._closing),
                                 return_exceptions=True)
