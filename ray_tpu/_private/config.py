"""Typed runtime flag registry with env overrides and head propagation.

The reference defines 218 ``RAY_CONFIG(type, name, default)`` flags
(reference: src/ray/common/ray_config_def.h), each overridable via a
``RAY_<name>`` env var, and the head node serializes its resolved config to
every joining node (``GetSystemConfig``, node_manager.proto:432). This is
the same capability with a TPU-sized surface:

- every tunable in the runtime lives here (one place to discover/tune);
- ``RAY_TPU_<NAME>`` env vars override defaults at process start;
- the GCS snapshots its resolved values and ships them to node managers in
  the ``register_node`` reply and to drivers/workers via
  ``get_system_config``, so one head-side setting governs the cluster.

Usage::

    from ray_tpu._private.config import cfg
    timeout = cfg.lease_idle_timeout_s

Values resolve in priority order: explicit ``cfg.apply()`` (propagated
snapshot) > ``RAY_TPU_*`` env var > registered default.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict, Optional

_ENV_PREFIX = "RAY_TPU_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


class _Flag:
    __slots__ = ("name", "type", "default", "doc")

    def __init__(self, name: str, typ: Callable, default: Any, doc: str):
        self.name = name
        self.type = typ
        self.default = default
        self.doc = doc

    def parse(self, raw: str) -> Any:
        if self.type is bool:
            return _parse_bool(raw)
        return self.type(raw)


_REGISTRY: Dict[str, _Flag] = {}


def _flag(name: str, typ: Callable, default: Any, doc: str) -> None:
    _REGISTRY[name] = _Flag(name, typ, default, doc)


# ----------------------------------------------------------------- registry
# Core worker / task submission
_flag("lease_idle_timeout_s", float, 1.0,
      "How long a granted worker lease may sit idle before being returned "
      "to the node manager.")
_flag("task_max_retries", int, 3,
      "Default retry budget for tasks whose worker died (mirrors "
      "@remote(max_retries=...) default).")
_flag("max_dispatchers_per_sig", int, 32,
      "Max concurrent lease-holding dispatchers per (resources, scheduling) "
      "task signature in one submitter process.")
_flag("actor_restart_probe_s", float, 0.2,
      "Delay before probing the GCS for a restarted actor's new address "
      "after an actor connection drops.")
_flag("wait_poll_floor_s", float, 0.02,
      "Floor for KV/rendezvous polling sleeps.")
_flag("lineage_max_depth", int, 16,
      "Maximum reconstruction attempts per lost object (bounds recursive "
      "lineage re-execution storms; reference caps lineage similarly via "
      "max_lineage_bytes / task retry budgets).")

# Worker hot paths
_flag("actor_push_batch", int, 32,
      "Max actor calls coalesced into one wire frame by the per-actor "
      "sender (amortizes frame + dispatch overhead; reference pipelines "
      "per-call over C++ gRPC, actor_task_submitter.h:75 — Python pays "
      "more per frame, so we batch).")
_flag("task_push_batch", int, 32,
      "Max queued same-signature tasks pushed to a leased worker in one "
      "frame.")
_flag("task_events_per_s", int, 2000,
      "Per-process task-event budget; beyond it the recorder keeps a "
      "deterministic 1-in-8 sample by task id (all states of sampled "
      "tasks are kept, so the timeline stays representative).")
_flag("gcs_wal_fsync", bool, False,
      "fsync the GCS write-ahead log after every append. Off by default: "
      "the WAL then survives a process kill but not a host crash (the "
      "snapshot still bounds loss to the snapshot interval). Turn on for "
      "single-head clusters whose state must survive power loss.")
_flag("inline_exec_threshold_s", float, 0.002,
      "Actor/task methods whose running-average duration is below this "
      "execute inline on the event loop instead of a thread-pool hop "
      "(adaptive: first call always measures on the pool; a method that "
      "turns slow migrates back).")

# Node manager
_flag("transfer_chunk_bytes", int, 8 * 1024 * 1024,
      "Chunk size for node-to-node object transfer (reference default is "
      "5 MiB, object_manager.h).")
_flag("push_window_chunks", int, 4,
      "Chunks in flight per push stream: pipelines the wire without "
      "unbounded receiver buffering (reference: PushManager per-push "
      "in-flight cap, push_manager.h:30).")
_flag("data_plane_enabled", bool, True,
      "Advertise and use the raw-socket binary data plane for cross-node "
      "object transfer (sender writes arena memoryviews, receiver "
      "recv_into()s straight into store.create regions). Off = legacy "
      "msgpack chunks on the control-plane RPC connection.")
_flag("transfer_streams", int, 2,
      "Parallel data-plane connections a large object push is striped "
      "across (per-stripe contiguous offset ranges). More streams help "
      "multi-core nodes overlap kernel copies; each stream keeps its own "
      "push_window_chunks flow-control window.")
_flag("transfer_stripe_min_bytes", int, 8 * 1024 * 1024,
      "Minimum bytes per stripe before a push fans out across an "
      "additional data-plane connection (small objects stay on one "
      "stream; striping overhead would dominate).")
_flag("transfer_streams_large", int, 8,
      "Stream count for weight-sized transfers: objects at or above "
      "transfer_large_object_bytes stripe across this many data-plane "
      "connections instead of transfer_streams (multi-GB weight "
      "broadcasts want every core's kernel copy bandwidth; small "
      "transfers keep the low default). <= transfer_streams disables "
      "the escalation.")
_flag("transfer_large_object_bytes", int, 256 * 1024 * 1024,
      "Size threshold at which a transfer counts as weight-sized and "
      "fans out across transfer_streams_large connections.")
_flag("pull_inflight_bytes", int, 256 * 1024 * 1024,
      "Admission budget for concurrent inbound object transfers on one "
      "node; pulls past it queue FIFO (reference: PullManager "
      "admission-controlled bundles, pull_manager.h:52).")
_flag("heartbeat_interval_s", float, 0.5,
      "Node manager -> GCS heartbeat period (also carries the resource "
      "view).")
_flag("streaming_backpressure", int, 16,
      "Max unconsumed items a streaming-generator task may have in "
      "flight before the executor pauses the generator (reference: "
      "_generator_backpressure_num_objects on ReportGeneratorItemReturns"
      ", core_worker.proto:400).")
_flag("gcs_reconnect_timeout_s", float, 60.0,
      "How long a node manager keeps retrying an unreachable GCS before "
      "giving up, reaping its workers, and exiting (reference: raylet "
      "gcs_rpc_server_reconnect_timeout_s, src/ray/raylet/main.cc:123 "
      "— round 4 leaked node managers retried forever).")
_flag("view_refresh_s", float, 1.0,
      "Period for refreshing the cluster resource view used by spillback "
      "scheduling.")
_flag("lease_wait_timeout_s", float, 300.0,
      "Server-side cap on how long a lease request may queue for local "
      "resources before erroring.")
_flag("actor_resource_wait_s", float, 60.0,
      "How long actor creation waits for local resources before failing.")
_flag("infeasible_grace_s", float, 30.0,
      "How long a request may be cluster-wide infeasible before it is "
      "failed (it stays queued as autoscaler demand until then).")
_flag("spill_uri", str, "",
      "Spill target as a URI (empty = node-local directory). Any "
      "fsspec-resolvable scheme works — gs://bucket/spill on TPU pods, "
      "s3://, memory:// in tests (reference: external_storage.py "
      "filesystem-or-cloud spill).")
_flag("spill_check_interval_s", float, 2.0,
      "Period of the object-spill pressure check loop.")
_flag("spill_high_watermark", float, 0.8,
      "Arena utilization above which primary copies spill to disk.")
_flag("log_tail_interval_s", float, 0.5,
      "Period of the worker-log tail loop feeding the driver log stream.")

# GCS
_flag("node_death_timeout_s", float, 5.0,
      "Heartbeat silence after which the GCS declares a node dead.")
_flag("gcs_snapshot_interval_s", float, 2.0,
      "Period between GCS table snapshots to disk (fault-tolerance "
      "restore source).")
_flag("health_check_interval_s", float, 0.5,
      "GCS-side period for scanning node liveness.")

# Object store
_flag("object_store_memory", int, 0,
      "Default per-node object store arena size in bytes (0 = auto).")
_flag("arena_stripes", int, 0,
      "Number of independently locked sub-heaps the shared-memory arena "
      "is striped into (0 = auto: RAY_TPU_ARENA_STRIPES env, else "
      "size/128MiB capped at 8). More stripes let more same-node clients "
      "put in parallel; the largest single object must fit one stripe.")
_flag("spill_probe_interval_puts", int, 32,
      "How many puts a worker may do between refreshes of its cached "
      "store-usage snapshot for the spill-pressure check (the probe also "
      "refreshes immediately on MemoryError; between refreshes the worker "
      "accounts its own put bytes locally).")
_flag("memory_monitor_interval_s", float, 1.0,
      "Period of the per-node worker memory monitor (0 disables).")
_flag("memory_usage_threshold", float, 0.95,
      "Fraction of system memory above which the node manager kills the "
      "largest retriable worker (OOM defense).")

_flag("pip_worker_idle_timeout_s", float, 300.0,
      "Idle eviction for workers dedicated to a pip runtime env (they "
      "serve exactly one env and would otherwise live forever).")
_flag("slice_wait_timeout_s", float, 60.0,
      "How long a gang waits for a whole healthy TPU slice before "
      "failing the attempt.")
_flag("spill_low_watermark", float, 0.6,
      "Spilling stops once arena utilization falls below this fraction.")
# Observability: time-series metrics plane (GCS) + registry pusher
_flag("metrics_push_interval_s", float, 2.0,
      "Base cadence of the per-process metrics registry push to the GCS "
      "(each push is jittered +/-25% so a fleet of workers doesn't "
      "synchronize on the control plane).")
_flag("metrics_ts_retention_s", float, 600.0,
      "How far back the GCS time-series plane keeps metric samples; "
      "windowed query_metrics() calls can look back at most this far.")
_flag("metrics_ts_max_samples", int, 600,
      "Per-series ring capacity in the GCS time-series plane (at the "
      "2s push cadence, 600 samples ~= 20 minutes per pushing process).")
_flag("metrics_ts_max_series", int, 4096,
      "Total (metric, tags, worker) series the GCS time-series plane "
      "retains; new series past the cap are counted and dropped.")
# Observability: GCS hot-path tracing + launch attribution (gcs_obs.py)
_flag("gcs_slow_rpc_ms", float, 50.0,
      "A GCS handler call slower than this emits a gcs.rpc span onto "
      "the runtime-event timeline (always, regardless of sampling); "
      "faster calls are sampled 1-in-gcs_rpc_sample_n. 0 disables the "
      "span path entirely (histograms still accumulate).")
_flag("gcs_rpc_sample_n", int, 100,
      "Sample rate for FAST handler spans: every Nth sub-threshold call "
      "per handler also emits a gcs.rpc span (0 = slow calls only). "
      "Latency/inflight histograms always record every call.")
_flag("gcs_obs_interval_s", float, 2.0,
      "Cadence of the GCS self-metrics loop (per-handler RPC "
      "histograms, pubsub backlog/latency, KV and table size gauges "
      "ingested into the time-series plane as worker 'gcs'). 0 "
      "disables the loop.")
_flag("launch_trace_enabled", bool, True,
      "Thread an actor.launch root span through GCS placement, node "
      "manager resource wait/worker obtain, and worker callable init, "
      "so every actor/replica launch renders as a phase-decomposed "
      "track in `ray_tpu timeline` and feeds the "
      "runtime_launch_phase_ms{phase} gauges.")
# Observability: crash black boxes (blackbox.py)
_flag("blackbox_enabled", bool, True,
      "Every daemon (GCS, node managers, workers) mirrors its flight-"
      "recorder ring and periodic metrics snapshots to a bounded "
      "on-disk NDJSON black box, sealed on clean exit / SIGTERM / "
      "GCS-disconnect death. `ray_tpu blackbox` stitches surviving "
      "boxes into one cross-node post-mortem timeline.")
_flag("blackbox_dir", str, "",
      "Directory for black-box files (empty = "
      "/tmp/raytpu/<session>/blackbox). One <process>-<pid>.bbox.ndjson "
      "per process plus at most one rotated .1 segment each.")
_flag("blackbox_max_bytes", int, 4 * 1024 * 1024,
      "Per-process black-box size bound: the live segment rotates to a "
      "single .1 segment at half this, so live+rotated never exceed it.")
_flag("blackbox_metrics_interval_s", float, 5.0,
      "Cadence of the black box's metrics-registry snapshot records "
      "(the 'last known metrics' a post-mortem sees for a SIGKILL'd "
      "process). 0 disables periodic snapshots (seal still writes one).")
# Observability: object-lifetime ledger (GCS object_ledger table)
_flag("ledger_enabled", bool, True,
      "Maintain per-object provenance records (creator, owner, size, "
      "placement, lifecycle timestamps, location set) in the GCS "
      "object_ledger table. Workers record create/seal/free events; node "
      "managers reconcile presence + pin counts at "
      "ledger_report_interval_s. Off = `ray_tpu memory` falls back to "
      "the local arena + owned-table view only.")
_flag("ledger_leak_after_s", float, 30.0,
      "A sealed, resident object with no pins whose owner exited (or "
      "reports zero references) older than this is flagged as leaked by "
      "the GCS ledger sweep (gauge store_leaked_bytes + store.leak "
      "instants + an eviction hint to the holding node's sweep).")
_flag("ledger_sweep_interval_s", float, 5.0,
      "Period of the GCS leak-detector sweep over the object ledger "
      "(0 disables the loop; the ledger_sweep handler still works).")
_flag("ledger_report_interval_s", float, 5.0,
      "Period of each node manager's arena census push into the object "
      "ledger (presence, pin counts, stripe/span placement). The census "
      "is the authority for an object's current location set — LRU "
      "evictions emit no event and are reconciled here.")
_flag("ledger_max_entries", int, 20000,
      "Object-ledger table capacity in the GCS; past it, freed rows are "
      "retired first, then the oldest rows (same bounded-ring discipline "
      "as the task-event sink).")
# Disaggregated serving: cluster-wide prefix routing (serve/disagg.py)
_flag("prefix_summary_interval_s", float, 2.0,
      "Cadence at which a prefix-routed serving replica publishes its "
      "radix-trie summary (top-K path fingerprints) to the GCS "
      "prefix_summaries table.")
_flag("prefix_summary_ttl_s", float, 10.0,
      "A prefix summary older than this is expired at read time — a "
      "dead replica stops attracting cluster-prefix routes within one "
      "TTL without explicit teardown.")
_flag("prefix_summary_top_k", int, 128,
      "Fingerprints per published trie summary (most recently touched "
      "first); ~8 bytes each on the wire, so the default is ~1KB per "
      "replica per publish.")
# Multi-model fleet plane (serve/fleet.py)
_flag("fleet_shell_pool_size", int, 1,
      "Pre-warmed replica shells the fleet manager keeps pooled for "
      "scale-to-zero revivals (process + imports paid; the deployment's "
      "callable/weights attach at cold start). 0 disables pooling — "
      "revivals fall back to a cold replica build.")
_flag("fleet_cold_start_timeout_s", float, 60.0,
      "How long a router holds requests for a scaled-to-zero deployment "
      "while a revival is in flight before surfacing no-replicas "
      "(serve/handle.py hold queue).")
_flag("fleet_attach_timeout_s", float, 120.0,
      "Per-shell attach RPC deadline during a revival (callable "
      "construction + weight load + warmup inside the shell); past it "
      "the shell is discarded and the next shell (or a cold build) "
      "serves the revival.")
_flag("prefix_summary_push", bool, True,
      "Push prefix_summaries table changes to routers over the serve "
      "long-poll plane (the controller snapshots the GCS table each "
      "reconcile tick and bumps listeners on change). Off = routers "
      "fall back to the 1 Hz GCS pull.")
# Serve tenancy (serve/fleet.py TenantAdmission; GCS tenant_quotas table)
_flag("tenant_default_quota", int, 0,
      "Default per-tenant concurrency quota at the serve ingress "
      "(max in-flight requests per tenant). <= 0 = unlimited, which "
      "keeps untagged traffic zero-cost; per-tenant overrides live in "
      "the GCS tenant_quotas table (serve.set_tenant_quota).")
_flag("tenant_default_weight", float, 1.0,
      "Default deficit-round-robin weight for tenants queued at the "
      "serve ingress; a backlogged tenant's service share is "
      "proportional to its weight.")
_flag("tenant_queue_max", int, 64,
      "Per-tenant ingress wait-queue bound; requests past it are shed "
      "with 429 + Retry-After instead of collapsing the queue.")
_flag("tenant_retry_after_s", float, 1.0,
      "Fallback Retry-After hint attached to tenant-quota 429 responses "
      "when no token bucket exists for the tenant (bucketed tenants "
      "derive the hint from their actual refill deficit instead, so "
      "retries spread out rather than herding into synchronized waves).")
# Cluster-edge shared tenant quotas (serve/fleet.py QuotaLeaseClient;
# GCS quota_leases table)
_flag("quota_lease_interval_s", float, 2.0,
      "Cadence at which each ingress proxy renews its tenant-quota "
      "lease against the GCS (pushing local burn deltas and picking up "
      "epoch changes) — the metrics cadence of the shared fair-share "
      "plane.")
_flag("quota_lease_ttl_s", float, 10.0,
      "A proxy lease older than this is expired by the GCS (its rate "
      "share re-splits to the survivors) and a proxy that cannot renew "
      "for this long degrades itself to the conservative local quota.")
_flag("quota_lease_conservative_frac", float, 0.25,
      "Fraction of its last known per-tenant rate share a proxy keeps "
      "admitting at while its lease is revoked or unrenewable. The GCS "
      "escrows a revoked proxy's share (it is NOT re-split until the "
      "lease expires or re-acquires), so conservative admission below "
      "the escrowed share can never over-admit cluster-wide.")
# Cluster-wide KV fabric (serve/disagg.py decode->decode hand-off)
_flag("kv_fabric_enabled", bool, True,
      "Let a decode replica pull prefix KV blocks from ANY peer replica "
      "whose published trie summary covers the prompt (decode->decode "
      "hand-off over the data plane) before falling back to the prefill "
      "tier and then to local prefill. Off = prefill-tier funnel only.")
_flag("kv_fabric_relay_min", int, 2,
      "Minimum number of concurrent same-fingerprint export waiters on "
      "distinct nodes before the exporter relays the payload through "
      "the broadcast tree instead of serving point-to-point pulls.")
# Multi-model fleet plane: weight source for shell attach / revival
_flag("fleet_weights_from_arena", bool, True,
      "Deployments whose weights come from a params_fn resolve them "
      "through the cluster weight plane by default: the first replica "
      "to construct the callable publishes the loaded tree via "
      "broadcast_weights (plain put when the plane is unavailable) and "
      "records the ref in the GCS KV; every later attach — shell "
      "revivals included — gets the tree from its local arena instead "
      "of re-running the loader. Off = every attach re-runs params_fn.")
# Elastic MPMD pipeline training (train/mpmd.py)
_flag("mpmd_replay_depth", int, 2,
      "Steps of input microbatches the MPMD pipeline controller retains "
      "in its bounded replay buffer; a stage lost to preemption can "
      "rejoin from a shard checkpoint at most this many steps old, so "
      "recovery replays <= replay_depth + 1 steps.")
_flag("mpmd_barrier_deadline_s", float, 30.0,
      "How long surviving pipeline stages may take to park (abort the "
      "in-flight step and roll back to the checkpoint boundary) after a "
      "stage loss; a survivor that misses the barrier degrades the "
      "recovery to a job-level failure instead of hanging the pipeline.")
_flag("mpmd_restart_backoff_s", float, 1.0,
      "Delay before re-provisioning a lost pipeline stage (and between "
      "consecutive stage-replace attempts).")
_flag("mpmd_health_poll_s", float, 0.5,
      "Cadence of the per-stage preemption-notice watch thread "
      "(tpu.check_preemption_notice + the per-stage marker file).")
_flag("mpmd_step_timeout_s", float, 300.0,
      "Deadline for one pipeline step's optimizer-apply barrier; past "
      "it the controller treats unresponsive stages as lost.")
# Object store: spanning-object spill (weight-distribution plane)
_flag("span_spill_min_idle_s", float, 5.0,
      "A sealed, unpinned spanning object younger than this is never "
      "spilled by the pressure sweep (a weight blob mid-broadcast is "
      "briefly unpinned between the relay write and the first consumer "
      "attach; age-gating keeps the sweep off that window).")
# NOTE: RPC chaos injection is configured through rpc.py's own
# RAY_TPU_TESTING_RPC_FAILURE spec string ("method=prob"), not a flag here.


class Config:
    """Resolved view over the registry; thread-safe; importable singleton."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._explicit: Dict[str, Any] = {}

    def __getattr__(self, name: str) -> Any:
        flag = _REGISTRY.get(name)
        if flag is None:
            raise AttributeError(f"unknown ray_tpu config flag {name!r}")
        with self._lock:
            if name in self._explicit:
                return self._explicit[name]
        raw = os.environ.get(_ENV_PREFIX + name.upper())
        if raw is not None:
            try:
                return flag.parse(raw)
            except (TypeError, ValueError):
                raise ValueError(
                    f"bad value {raw!r} for {_ENV_PREFIX}{name.upper()} "
                    f"(expected {flag.type.__name__})")
        return flag.default

    def set(self, name: str, value: Any) -> None:
        flag = _REGISTRY.get(name)
        if flag is None:
            raise KeyError(f"unknown ray_tpu config flag {name!r}")
        with self._lock:
            self._explicit[name] = value

    def reset(self, name: Optional[str] = None) -> None:
        with self._lock:
            if name is None:
                self._explicit.clear()
            else:
                self._explicit.pop(name, None)

    def snapshot(self) -> Dict[str, Any]:
        """Fully-resolved {name: value} map — what the head ships to
        joining nodes so the whole cluster runs one config."""
        return {name: getattr(self, name) for name in _REGISTRY}

    def apply(self, values: Dict[str, Any]) -> None:
        """Apply a propagated snapshot (unknown keys are ignored so a
        newer head can talk to an older node)."""
        for k, v in values.items():
            if k in _REGISTRY:
                with self._lock:
                    self._explicit[k] = v

    def describe(self) -> str:
        lines = []
        for name, flag in sorted(_REGISTRY.items()):
            cur = getattr(self, name)
            mark = "" if cur == flag.default else "  [override]"
            lines.append(f"{name} = {cur!r}{mark}\n    {flag.doc}")
        return "\n".join(lines)


cfg = Config()


def flags() -> Dict[str, _Flag]:
    return dict(_REGISTRY)
