"""Node bootstrap: starts the GCS and node-manager daemons for a local
cluster and connects the driver (reference: python/ray/_private/node.py:37 and
services.py process launchers)."""

from __future__ import annotations

import atexit
import json
import os
import subprocess
import sys
import time
import uuid
from typing import Dict, Optional


class ProcessHandle:
    def __init__(self, proc: subprocess.Popen, announced: Dict[str, str]):
        self.proc = proc
        self.announced = announced

    def kill(self):
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def _launch(cmd, keys, timeout=30.0, env=None,
            log_path: Optional[str] = None,
            detached: bool = False) -> ProcessHandle:
    """Start a daemon and read `KEY=value` announce lines from stdout.
    stderr goes to a session log file so daemons never hold the driver's
    (or pytest's) pipes open. Unless detached, the child arms
    PR_SET_PDEATHSIG so it dies with this process even on SIGKILL
    (round-4 fix: daemons used to outlive crashed drivers forever)."""
    if log_path:
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        errf = open(log_path, "ab")
    else:
        errf = subprocess.DEVNULL
    if not detached:
        from ray_tpu._private.proc_util import child_env
        env = child_env(env)
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stdin=subprocess.DEVNULL, text=True, env=env,
                            stderr=errf, start_new_session=True)
    if log_path:
        errf.close()
    announced: Dict[str, str] = {}
    deadline = time.monotonic() + timeout
    remaining = set(keys)
    while remaining:
        if time.monotonic() > deadline:
            proc.kill()
            raise TimeoutError(f"{cmd[2]} did not announce {remaining}")
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"{' '.join(cmd[:4])} exited with {proc.returncode}")
            time.sleep(0.01)
            continue
        line = line.strip()
        if "=" in line:
            k, v = line.split("=", 1)
            if k in remaining:
                announced[k] = v
                remaining.discard(k)
    # stop consuming stdout; let the daemon write freely (pipe may fill
    # otherwise — redirect the rest to devnull via a drain thread)
    import threading

    def drain():
        try:
            for _ in proc.stdout:
                pass
        except Exception:
            pass

    threading.Thread(target=drain, daemon=True).start()
    return ProcessHandle(proc, announced)


class LocalNode:
    """A head node: GCS + node manager as subprocesses."""

    def __init__(self, gcs_handle: Optional[ProcessHandle],
                 nm_handle: ProcessHandle, gcs_address: str,
                 session_name: str):
        self.gcs_handle = gcs_handle
        self.nm_handle = nm_handle
        self.gcs_address = gcs_address
        self.session_name = session_name
        self.node_address = nm_handle.announced["NODE_ADDRESS"]
        self.node_id = nm_handle.announced["NODE_ID"]
        self.store_path = nm_handle.announced["STORE_PATH"]

    def kill(self):
        self.nm_handle.kill()
        if self.gcs_handle is not None:
            self.gcs_handle.kill()
        try:
            os.unlink(self.store_path)
        except OSError:
            pass


def start_head(num_cpus: Optional[float] = None,
               resources: Optional[Dict[str, float]] = None,
               object_store_memory: Optional[int] = None,
               labels: Optional[Dict[str, str]] = None,
               session_name: Optional[str] = None,
               gcs_port: int = 0, detached: bool = False) -> LocalNode:
    session_name = session_name or f"s{uuid.uuid4().hex[:8]}"
    gcs = _launch([sys.executable, "-m", "ray_tpu._private.gcs",
                   "--port", str(gcs_port), "--session-name", session_name],
                  ["GCS_ADDRESS"],
                  log_path=f"/tmp/raytpu/{session_name}/logs/gcs.err",
                  detached=detached)
    gcs_address = gcs.announced["GCS_ADDRESS"]
    node = start_node(gcs_address, num_cpus=num_cpus, resources=resources,
                      object_store_memory=object_store_memory, labels=labels,
                      session_name=session_name, detached=detached)
    return LocalNode(gcs, node.nm_handle, gcs_address, session_name)


def start_node(gcs_address: str, num_cpus: Optional[float] = None,
               resources: Optional[Dict[str, float]] = None,
               object_store_memory: Optional[int] = None,
               labels: Optional[Dict[str, str]] = None,
               session_name: str = "session",
               gcs_address_source: Optional[str] = None,
               detached: bool = False) -> LocalNode:
    res = dict(resources or {})
    if num_cpus is not None:
        res["CPU"] = float(num_cpus)
    cmd = [sys.executable, "-m", "ray_tpu._private.node_manager",
           "--gcs-address", gcs_address,
           "--resources", json.dumps(res),
           "--labels", json.dumps(labels or {}),
           "--session-name", session_name]
    if gcs_address_source:
        cmd += ["--gcs-address-source", gcs_address_source]
    if not object_store_memory:
        from ray_tpu._private.config import cfg
        object_store_memory = cfg.object_store_memory or None
    if object_store_memory:
        cmd += ["--store-bytes", str(int(object_store_memory))]
    nm = _launch(cmd, ["NODE_ADDRESS", "NODE_ID", "STORE_PATH"],
                 log_path=f"/tmp/raytpu/{session_name}/logs/node_manager.err",
                 detached=detached)
    return LocalNode(None, nm, gcs_address, session_name)
