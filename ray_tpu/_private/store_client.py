"""GCS store clients: the persistence interface behind the GCS
(reference: src/ray/gcs/store_client/ — StoreClient ABC with
Redis/in-memory/observable implementations; redis_store_client.h:106 is
the synchronous durable write the WAL mirrors here).

Two implementations:
- FileStoreClient — node-local snapshot + write-ahead log + address
  file. Survives GCS process death; head-node disk loss loses the
  cluster (the round-3 status quo, now behind the interface).
- ExternalStoreClient — snapshot + address on any fsspec URI
  (gs://bucket/..., memory:// in tests) via ray_tpu.util.storage, so a
  replacement GCS on a DIFFERENT host can restart from the store the
  way the reference's Redis-backed GCS-FT does. Object stores don't
  append, so the WAL degrades to snapshot-interval durability — the
  trade is stated here rather than hidden.

The address file is the discovery channel: the GCS writes its live
address on startup; node managers that lose the GCS re-read it before
reconnecting, so a restart on a new port/host heals without restarting
the raylets (reference: raylets re-resolve the GCS address from Redis).
"""

from __future__ import annotations

import logging
import os
from typing import Iterator, Optional, Tuple

logger = logging.getLogger(__name__)


class StoreClient:
    """Durable state for one GCS instance."""

    #: False when wal_append is a no-op — callers skip serializing the
    #: record at all (per-mutation msgpack on the GCS hot path)
    wal_enabled: bool = True

    def save_snapshot(self, blob: bytes) -> None:
        raise NotImplementedError

    def load_snapshot(self) -> Optional[bytes]:
        raise NotImplementedError

    def wal_append(self, record: bytes) -> None:
        raise NotImplementedError

    def wal_records(self) -> Iterator[bytes]:
        raise NotImplementedError

    def wal_reset(self) -> None:
        """Called after a snapshot covers everything the WAL recorded."""
        raise NotImplementedError

    def write_address(self, address: str) -> None:
        raise NotImplementedError

    def read_address(self) -> Optional[str]:
        raise NotImplementedError


class FileStoreClient(StoreClient):
    """Snapshot at `path`, WAL at `path.wal`, address at `path.addr`."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._wal = None

    # ------------------------------------------------------------ snapshot
    def save_snapshot(self, blob: bytes) -> None:
        tmp = f"{self.path}.tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, self.path)

    def load_snapshot(self) -> Optional[bytes]:
        if not os.path.exists(self.path):
            return None
        with open(self.path, "rb") as f:
            return f.read()

    # ----------------------------------------------------------------- wal
    def wal_append(self, record: bytes) -> None:
        if self._wal is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._wal = open(self.path + ".wal", "ab")
        self._wal.write(len(record).to_bytes(4, "little") + record)
        self._wal.flush()
        if self.fsync:
            os.fsync(self._wal.fileno())

    def wal_records(self) -> Iterator[bytes]:
        path = self.path + ".wal"
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            raw = f.read()
        off = 0
        while off + 4 <= len(raw):
            ln = int.from_bytes(raw[off:off + 4], "little")
            if off + 4 + ln > len(raw):
                break          # torn tail write: ignore
            yield raw[off + 4:off + 4 + ln]
            off += 4 + ln

    def wal_reset(self) -> None:
        if self._wal is not None:
            try:
                self._wal.close()
            except Exception:
                pass
            self._wal = None
        try:
            os.unlink(self.path + ".wal")
        except OSError:
            pass

    # ------------------------------------------------------------- address
    def write_address(self, address: str) -> None:
        tmp = f"{self.path}.addr.tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            f.write(address)
        os.replace(tmp, self.path + ".addr")

    def read_address(self) -> Optional[str]:
        try:
            with open(self.path + ".addr") as f:
                return f.read().strip() or None
        except OSError:
            return None


class ExternalStoreClient(StoreClient):
    """Snapshot + address on an fsspec URI. No append on object stores,
    so mutations between snapshots are NOT durable here — durability is
    the snapshot interval (documented trade; the reference's Redis gives
    per-write durability, a future external impl with a log-capable
    backend can too)."""

    wal_enabled = False

    def __init__(self, uri: str):
        from ray_tpu.util import storage
        self._s = storage
        self.uri = uri.rstrip("/")

    def save_snapshot(self, blob: bytes) -> None:
        self._s.write_bytes(f"{self.uri}/snapshot.bin", blob)

    def load_snapshot(self) -> Optional[bytes]:
        if not self._s.exists(f"{self.uri}/snapshot.bin"):
            return None
        return self._s.read_bytes(f"{self.uri}/snapshot.bin")

    def wal_append(self, record: bytes) -> None:
        pass    # see class docstring

    def wal_records(self) -> Iterator[bytes]:
        return iter(())

    def wal_reset(self) -> None:
        pass

    def write_address(self, address: str) -> None:
        self._s.write_bytes(f"{self.uri}/gcs.addr",
                            address.encode("utf-8"))

    def read_address(self) -> Optional[str]:
        if not self._s.exists(f"{self.uri}/gcs.addr"):
            return None
        return self._s.read_bytes(f"{self.uri}/gcs.addr") \
            .decode("utf-8").strip() or None


def store_client_for(target: str, fsync: bool = False) -> StoreClient:
    """path -> FileStoreClient; URI (scheme://) -> ExternalStoreClient."""
    from ray_tpu.util import storage
    scheme, path = storage._split(target)
    if scheme:
        client = ExternalStoreClient(target)
        if not _WARNED_EXTERNAL_WAL.get(scheme):
            _WARNED_EXTERNAL_WAL[scheme] = True
            logger.warning(
                "gcs persistence on %s:// disables the WAL: durability "
                "is the snapshot interval, not per-mutation as with a "
                "local path (gcs_wal_fsync ignored). The reference's "
                "Redis store client persists every write.", scheme)
        return client
    return FileStoreClient(path, fsync=fsync)


_WARNED_EXTERNAL_WAL: dict = {}
