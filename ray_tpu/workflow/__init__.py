"""Workflow: durable DAG execution with step-level checkpointing
(reference: python/ray/workflow/ — workflow_executor.py,
workflow_storage.py). Steps run as tasks; each step's result persists
under the workflow's storage dir, so a resumed run skips completed steps
and continues where it crashed."""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Callable, Dict, List, Optional

DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu_workflows")


class StepNode:
    def __init__(self, fn: Callable, args, kwargs, name: Optional[str] = None):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name or fn.__name__

    def _upstream(self):
        return ([a for a in self.args if isinstance(a, StepNode)]
                + [v for v in self.kwargs.values()
                   if isinstance(v, StepNode)])


class StepFunction:
    def __init__(self, fn: Callable, name: Optional[str] = None):
        self.fn = fn
        self.name = name or fn.__name__

    def bind(self, *args, **kwargs) -> StepNode:
        return StepNode(self.fn, args, kwargs, self.name)

    def options(self, name: Optional[str] = None) -> "StepFunction":
        return StepFunction(self.fn, name or self.name)


def step(fn: Callable = None, *, name: Optional[str] = None):
    if fn is not None:
        return StepFunction(fn)
    return lambda f: StepFunction(f, name)


def _topo(root: StepNode) -> List[StepNode]:
    order, seen = [], set()

    def visit(n):
        if id(n) in seen:
            return
        seen.add(id(n))
        for up in n._upstream():
            visit(up)
        order.append(n)

    visit(root)
    return order


def _step_key(node: StepNode, index: int) -> str:
    return f"{index:04d}_{node.name}"


def run(root: StepNode, *, workflow_id: str,
        storage: str = DEFAULT_STORAGE) -> Any:
    """Execute the DAG durably; completed steps are skipped on re-run
    (call run() again with the same workflow_id to resume)."""
    import ray_tpu

    wf_dir = os.path.join(storage, workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    order = _topo(root)
    results: Dict[int, Any] = {}
    for i, node in enumerate(order):
        key = _step_key(node, i)
        done_path = os.path.join(wf_dir, key + ".pkl")
        if os.path.exists(done_path):
            with open(done_path, "rb") as f:
                results[id(node)] = pickle.load(f)
            continue

        def resolve(a):
            return results[id(a)] if isinstance(a, StepNode) else a

        args = [resolve(a) for a in node.args]
        kwargs = {k: resolve(v) for k, v in node.kwargs.items()}
        remote_fn = ray_tpu.remote(node.fn)
        value = ray_tpu.get(remote_fn.remote(*args, **kwargs))
        tmp = done_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, done_path)
        results[id(node)] = value
    return results[id(root)]


def list_workflows(storage: str = DEFAULT_STORAGE) -> List[str]:
    if not os.path.isdir(storage):
        return []
    return sorted(os.listdir(storage))


def delete(workflow_id: str, storage: str = DEFAULT_STORAGE):
    import shutil
    shutil.rmtree(os.path.join(storage, workflow_id), ignore_errors=True)
