"""Workflow: durable DAG execution with step-level checkpointing
(reference: python/ray/workflow/ — workflow_executor.py,
workflow_storage.py). Steps run as tasks; each step's result persists
under the workflow's storage dir, so a resumed run skips completed steps
and continues where it crashed."""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Callable, Dict, List, Optional

DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu_workflows")


class StepNode:
    def __init__(self, fn: Callable, args, kwargs, name: Optional[str] = None):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name or fn.__name__

    def _upstream(self):
        return ([a for a in self.args if isinstance(a, StepNode)]
                + [v for v in self.kwargs.values()
                   if isinstance(v, StepNode)])


class StepFunction:
    def __init__(self, fn: Callable, name: Optional[str] = None):
        self.fn = fn
        self.name = name or fn.__name__

    def bind(self, *args, **kwargs) -> StepNode:
        return StepNode(self.fn, args, kwargs, self.name)

    def options(self, name: Optional[str] = None) -> "StepFunction":
        return StepFunction(self.fn, name or self.name)


def step(fn: Callable = None, *, name: Optional[str] = None):
    if fn is not None:
        return StepFunction(fn)
    return lambda f: StepFunction(f, name)


def _topo(root: StepNode) -> List[StepNode]:
    order, seen = [], set()

    def visit(n):
        if id(n) in seen:
            return
        seen.add(id(n))
        for up in n._upstream():
            visit(up)
        order.append(n)

    visit(root)
    return order


def _step_key(node: StepNode, index: int) -> str:
    return f"{index:04d}_{node.name}"


def run(root: StepNode, *, workflow_id: str,
        storage: str = DEFAULT_STORAGE) -> Any:
    """Execute the DAG durably; completed steps are skipped on re-run
    (call run() again with the same workflow_id to resume)."""
    import ray_tpu

    wf_dir = os.path.join(storage, workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    order = _topo(root)
    results: Dict[int, Any] = {}
    for i, node in enumerate(order):
        key = _step_key(node, i)
        done_path = os.path.join(wf_dir, key + ".pkl")
        if os.path.exists(done_path):
            with open(done_path, "rb") as f:
                results[id(node)] = pickle.load(f)
            continue

        def resolve(a):
            return results[id(a)] if isinstance(a, StepNode) else a

        if isinstance(node, EventNode):
            value = _await_event(wf_dir, node)
        else:
            args = [resolve(a) for a in node.args]
            kwargs = {k: resolve(v) for k, v in node.kwargs.items()}
            remote_fn = ray_tpu.remote(node.fn)
            value = ray_tpu.get(remote_fn.remote(*args, **kwargs))
        tmp = done_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, done_path)
        results[id(node)] = value
    return results[id(root)]


def list_workflows(storage: str = DEFAULT_STORAGE) -> List[str]:
    if not os.path.isdir(storage):
        return []
    return sorted(os.listdir(storage))


def delete(workflow_id: str, storage: str = DEFAULT_STORAGE):
    import shutil
    shutil.rmtree(os.path.join(storage, workflow_id), ignore_errors=True)


# --------------------------------------------------------------- events
class EventNode(StepNode):
    """A step that blocks the workflow until an external event arrives
    (reference: python/ray/workflow/ event system — HTTP/manual event
    providers resolved through durable storage). The event value is
    checkpointed like any step result, so a resumed run does not wait
    again."""

    def __init__(self, event_key: str, timeout_s: Optional[float] = None):
        super().__init__(fn=None, args=(), kwargs={},
                         name=f"event:{event_key}")
        self.event_key = event_key
        self.timeout_s = timeout_s


def wait_for_event(event_key: str,
                   timeout_s: Optional[float] = None) -> EventNode:
    return EventNode(event_key, timeout_s)


def send_event(workflow_id: str, event_key: str, value: Any = True,
               storage: str = DEFAULT_STORAGE) -> None:
    """Deliver an event to a (possibly waiting) workflow. Durable: events
    sent before the workflow reaches its wait step are consumed on
    arrival at the step."""
    ev_dir = os.path.join(storage, workflow_id, "events")
    os.makedirs(ev_dir, exist_ok=True)
    tmp = os.path.join(ev_dir, f".{event_key}.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(value, f)
    os.replace(tmp, os.path.join(ev_dir, event_key + ".pkl"))


def _await_event(wf_dir: str, node: "EventNode") -> Any:
    import time as _time
    path = os.path.join(wf_dir, "events", node.event_key + ".pkl")
    deadline = None if node.timeout_s is None else \
        _time.monotonic() + node.timeout_s
    while True:
        if os.path.exists(path):
            with open(path, "rb") as f:
                return pickle.load(f)
        if deadline is not None and _time.monotonic() > deadline:
            raise TimeoutError(
                f"workflow event {node.event_key!r} never arrived")
        _time.sleep(0.05)


# ------------------------------------------------------- virtual actors
class VirtualActor:
    """Durable stateful entity addressed by id: every method call loads
    the persisted state, executes as a task, and checkpoints the new
    state (reference: ray.workflow virtual actors — long-lived state
    machines that survive cluster restarts)."""

    def __init__(self, cls, actor_id: str, storage: str = DEFAULT_STORAGE):
        self._cls = cls
        self._actor_id = actor_id
        self._dir = os.path.join(storage, "virtual_actors",
                                 f"{cls.__name__}:{actor_id}")
        os.makedirs(self._dir, exist_ok=True)

    def _state_path(self) -> str:
        return os.path.join(self._dir, "state.pkl")

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        cls = self._cls
        state_path = self._state_path()

        def call(*args, **kwargs):
            import ray_tpu

            def run_method(state_blob, method, args, kwargs):
                import pickle as p
                inst = cls.__new__(cls)
                if state_blob is not None:
                    inst.__dict__.update(p.loads(state_blob))
                else:
                    inst.__init__()
                out = getattr(inst, method)(*args, **kwargs)
                return p.dumps(inst.__dict__), out

            blob = None
            if os.path.exists(state_path):
                with open(state_path, "rb") as f:
                    blob = f.read()
            remote = ray_tpu.remote(run_method)
            new_blob, out = ray_tpu.get(
                remote.remote(blob, name, args, kwargs))
            tmp = state_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(new_blob)
            os.replace(tmp, state_path)
            return out

        return call


def get_actor(cls, actor_id: str,
              storage: str = DEFAULT_STORAGE) -> VirtualActor:
    """Get-or-create a durable virtual actor."""
    return VirtualActor(cls, actor_id, storage)
