"""Multi-model fleet plane (ROADMAP item 3): scale-to-zero with
pre-warmed shells, per-tenant fair-share admission, and burn-aware
shedding.

The production shape this module serves is hundreds of models sharing
one TPU fleet (reference: Ray Serve multi-app + autoscaler-v2). Three
problems define it, and three pieces here solve them:

- **Scale-to-zero + cold-start pooling.** A deployment opts in via
  ``AutoscalingConfig(min_replicas=0, idle_scale_to_zero_s=...)``. The
  ordinary autoscaling policy floors at ONE replica; only the fleet
  manager's idle reaper (:func:`decide_scale_to_zero`) takes the last
  step to zero, after the load has been zero for the full idle window.
  Revival goes through a shared :class:`ShellPool` of pre-warmed
  replica *shells* (:class:`ReplicaShell`: a live actor process with
  the heavy imports already paid, no callable, no weights). On the
  first request the router parks callers in a hold queue (the handle-
  level analog of the scheduler's ``submit(hold=)``, serve/handle.py
  ``_hold_for_revival``) and asks the controller to revive; the fleet
  manager checks a shell out, attaches the deployment's callable to it
  (weights load inside the already-warm process — an LLMDeployment's
  ``params_fn`` resolves through the PR 11 weight plane BY DEFAULT:
  ``serve/weights.py resolve_weight_source`` attaches the recorded
  broadcast tree zero-copy from the local arena and only the very first
  attach cluster-wide runs the loader, with a plain-put fallback when
  the plane is unavailable — ``fleet_weights_from_arena`` flag), lets
  the callable's ``on_shell_attach`` hook warm its compiled programs,
  and only then publishes the replica to routing tables. Cold-start
  latency is measured per revival and exported as
  ``serve_cold_start_ms``.

- **Per-tenant fair-share admission.** Requests carry a tenant
  (``X-RayTPU-Tenant`` header at the proxy, ``options(tenant=)`` at the
  handle). The ingress runs :class:`TenantAdmission`: weighted
  deficit-round-robin (:class:`DeficitRoundRobin`) across per-tenant
  FIFO queues with per-tenant concurrency quotas (GCS ``tenant_quotas``
  table, ``serve.set_tenant_quota``). Over-quota work is rejected with
  429 + ``Retry-After`` instead of collapsing the queue; a
  quota-respecting tenant's service share can never be pushed below its
  DRR weight by a hot neighbour. Exported: ``serve_tenant_qps``,
  ``serve_tenant_shed_total``.

- **Burn-aware shedding + spread placement.** A deployment may declare
  ``fallback_model=<smaller deployment, same API>``: when its replicas
  are saturated the handle routes overflow down the fallback ladder
  (``serve_fallback_shed_total``), and the controller's burn loop
  prefers shedding to asking the cluster autoscaler for new slices
  while the fallback has headroom (:func:`fallback_has_headroom`).
  Replica placement gains anti-affinity (:func:`plan_spread`): one
  deployment's replicas spread across distinct nodes so a single
  preemption cannot zero a model.

Everything policy-shaped here is pure (injectable clocks, no cluster
imports at decision time) so the tier-1 suite drives it hermetically;
the :class:`FleetManager` adds the controller-side threading.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private.config import cfg

logger = logging.getLogger(__name__)


# ------------------------------------------------------------ pure policy
def decide_scale_to_zero(auto: Optional[Dict], idle_since: Optional[float],
                         now: float, target: int, total_load: float,
                         reviving: bool = False
                         ) -> Tuple[bool, Optional[float]]:
    """The idle reaper's decision for one deployment: should the last
    replica go away NOW? Returns ``(scale_to_zero, idle_since')`` where
    ``idle_since'`` is the carried idle-window start (None = not idle).

    Only deployments that opted in (``min_replicas == 0`` AND
    ``idle_scale_to_zero_s`` set) ever scale to zero, and only after the
    probed load has been zero for the FULL idle window — the ordinary
    autoscaling policy floors at one replica precisely so this is the
    single code path that takes the last step. A revival in flight
    pins the deployment up (the fleet manager is mid-cold-start; reaping
    under it would strand the held requests)."""
    idle_s = (auto or {}).get("idle_scale_to_zero_s")
    if not idle_s or int((auto or {}).get("min_replicas", 1) or 0) > 0:
        return False, None
    if reviving or total_load > 0 or target <= 0:
        return False, None
    if idle_since is None:
        idle_since = now
    return (now - idle_since >= float(idle_s)), idle_since


def plan_spread(nodes: List[Dict], used_nodes: List[str]) -> Optional[str]:
    """Anti-affinity placement hint: the alive node hosting the FEWEST
    of this deployment's replicas (ties break to the most available
    CPU), so one preemption or node loss cannot zero a whole model.
    Returns None when there is no choice to make (<= 1 alive node)."""
    counts = collections.Counter(n for n in used_nodes if n)
    best_key, best_nid = None, None
    alive = [n for n in nodes if n.get("alive", True)]
    if len(alive) <= 1:
        return None
    for n in alive:
        nid = n.get("node_id")
        if not nid:
            continue
        avail = float((n.get("available") or {}).get("CPU", 0.0))
        key = (counts.get(nid, 0), -avail)
        if best_key is None or key < best_key:
            best_key, best_nid = key, nid
    return best_nid


def fallback_has_headroom(dep: Dict) -> bool:
    """True when a fallback deployment can absorb shed overflow: it has
    running replicas and its probed load sits under 80% of capacity.
    Caller holds the controller lock (reads in-memory state only)."""
    n = len(dep.get("replicas") or [])
    if n == 0:
        return False
    cap = int(dep["spec"]["config"].get("max_ongoing_requests", 16) or 16)
    load = float(sum(dep.get("loads") or []))
    return load < 0.8 * n * cap


# --------------------------------------------------- fair-share admission
class TenantQuotaExceeded(Exception):
    """Raised (and mapped to HTTP 429 + Retry-After at the proxy) when a
    tenant is over its concurrency quota and its DRR queue is full —
    load-shedding instead of queue collapse."""

    def __init__(self, tenant: str, retry_after_s: float):
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"tenant {tenant!r} over quota; retry after "
            f"{self.retry_after_s:.1f}s")


class DeficitRoundRobin:
    """Weighted deficit round robin over per-tenant FIFO queues
    (Shreedhar & Varghese). Each visit to the head of the active ring
    tops the tenant's deficit up by ``quantum * weight``; one unit of
    deficit buys one dequeued item. Backlogged tenants therefore share
    service in proportion to weight regardless of how deep a hot
    tenant's queue grows — the numeric fairness property the unit suite
    asserts. Not thread-safe; callers (TenantAdmission) hold their own
    lock."""

    def __init__(self, quantum: float = 1.0, default_weight: float = 1.0):
        self.quantum = float(quantum)
        self.default_weight = float(default_weight)
        self._w: Dict[str, float] = {}
        self._q: Dict[str, collections.deque] = {}
        self._deficit: Dict[str, float] = {}
        self._ring: collections.deque = collections.deque()
        self._in_ring: set = set()

    def set_weight(self, tenant: str, weight: float):
        self._w[tenant] = max(0.0, float(weight))

    def weight(self, tenant: str) -> float:
        return self._w.get(tenant, self.default_weight)

    def push(self, tenant: str, item: Any):
        q = self._q.get(tenant)
        if q is None:
            q = self._q[tenant] = collections.deque()
        q.append(item)
        if tenant not in self._in_ring:
            self._ring.append(tenant)
            self._in_ring.add(tenant)

    def queue_len(self, tenant: str) -> int:
        q = self._q.get(tenant)
        return len(q) if q else 0

    def __len__(self) -> int:
        return sum(len(q) for q in self._q.values())

    def _retire(self, tenant: str):
        # leaving the ring resets the deficit (standard DRR: an idle
        # tenant cannot bank service credit for a later burst)
        try:
            self._ring.remove(tenant)
        except ValueError:
            pass
        self._in_ring.discard(tenant)
        self._deficit.pop(tenant, None)
        self._q.pop(tenant, None)

    def pop(self, eligible: Optional[Callable[[str], bool]] = None
            ) -> Optional[Tuple[str, Any]]:
        """Dequeue the next item under DRR order, visiting only tenants
        for which ``eligible(tenant)`` is true (quota headroom). Returns
        None when nothing is serveable right now."""
        # bounded walk: each active tenant is visited at most twice (one
        # top-up may be needed before the deficit covers an item)
        for _ in range(2 * len(self._ring) + 2):
            if not self._ring:
                return None
            t = self._ring[0]
            q = self._q.get(t)
            if not q:
                self._retire(t)
                continue
            if eligible is not None and not eligible(t):
                self._ring.rotate(-1)
                continue
            if self._deficit.get(t, 0.0) < 1.0:
                self._deficit[t] = (self._deficit.get(t, 0.0)
                                    + self.quantum * self.weight(t))
                if self._deficit[t] < 1.0:
                    # weight < 1/quantum: banks credit across rounds
                    self._ring.rotate(-1)
                    continue
            item = q.popleft()
            self._deficit[t] -= 1.0
            if not q:
                self._retire(t)
            elif self._deficit[t] < 1.0:
                self._ring.rotate(-1)
            return t, item
        return None


class _Waiter:
    __slots__ = ("event", "granted", "abandoned")

    def __init__(self):
        self.event = threading.Event()
        self.granted = False
        self.abandoned = False


class TenantLease:
    """One admitted request's hold on its tenant's concurrency quota.
    Release exactly once (context-manager friendly)."""

    def __init__(self, admission: "TenantAdmission", tenant: str):
        self._adm = admission
        self.tenant = tenant
        self._done = False

    def release(self):
        if not self._done:
            self._done = True
            self._adm._release(self.tenant)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class TenantAdmission:
    """The ingress admission gate: per-tenant concurrency quotas with a
    weighted-DRR wait queue in front, and load shedding past the queue
    bound. Thread-safe (proxy executor threads call acquire/release
    concurrently).

    Semantics per ``acquire(tenant)``:

    1. under quota, queue empty       -> admitted immediately;
    2. over quota / capacity, queue
       under ``queue_max``            -> parks in the tenant's FIFO
       queue; grants follow DRR order as releases free capacity, so a
       backlogged quota-respecting tenant is served at >= its weight
       share no matter how hot a neighbour runs;
    3. queue full (or the wait times
       out)                           -> :class:`TenantQuotaExceeded`
       (429 + Retry-After at the proxy) — shedding, not collapse.

    Quotas/weights come from the GCS ``tenant_quotas`` table
    (``serve.set_tenant_quota``; the ``__default__`` row moves the
    fleet-wide defaults) via :meth:`maybe_refresh`; a quota <= 0 means
    unlimited, which keeps untagged traffic zero-cost by default.
    Exports ``serve_tenant_qps`` (5s sliding window of offered load)
    and ``serve_tenant_shed_total``."""

    QPS_WINDOW_S = 5.0

    def __init__(self, default_quota: Optional[int] = None,
                 default_weight: Optional[float] = None,
                 queue_max: Optional[int] = None,
                 total_limit: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        self.default_quota = int(cfg.tenant_default_quota
                                 if default_quota is None else default_quota)
        self.queue_max = int(cfg.tenant_queue_max
                             if queue_max is None else queue_max)
        self.total_limit = int(total_limit)
        self._clock = clock
        self._drr = DeficitRoundRobin(
            default_weight=(cfg.tenant_default_weight
                            if default_weight is None else default_weight))
        self._quota: Dict[str, int] = {}
        self._inflight: Dict[str, int] = {}
        self._total = 0
        self._lock = threading.Lock()
        self._qps: Dict[str, collections.deque] = {}
        self._refresh_t = 0.0
        self._metrics = None
        self.admitted_total: Dict[str, int] = collections.defaultdict(int)
        self.shed_total: Dict[str, int] = collections.defaultdict(int)
        # when a token bucket backs this tenant (QuotaLeaseClient at
        # the proxy), Retry-After derives from its actual refill
        # deficit instead of the fixed cfg constant — a fixed constant
        # herds every shed client into one synchronized retry wave.
        self.retry_hint: Optional[Callable[[str], Optional[float]]] = None

    # ----------------------------------------------------------- quotas
    def quota(self, tenant: str) -> int:
        return self._quota.get(tenant, self.default_quota)

    def set_quota(self, tenant: str, quota: Optional[int] = None,
                  weight: Optional[float] = None):
        with self._lock:
            self._apply_row_locked(tenant, quota, weight)

    def _apply_row_locked(self, tenant, quota, weight):
        if tenant == "__default__":
            if quota is not None:
                self.default_quota = int(quota)
            if weight is not None:
                self._drr.default_weight = float(weight)
            return
        if quota is not None:
            self._quota[tenant] = int(quota)
        if weight is not None:
            self._drr.set_weight(tenant, float(weight))

    def apply_quotas(self, rows: Optional[List[Dict]]):
        """Fold GCS ``tenant_quotas`` rows in (last write wins)."""
        with self._lock:
            for row in rows or []:
                t = row.get("tenant")
                if t:
                    self._apply_row_locked(t, row.get("quota"),
                                           row.get("weight"))

    def maybe_refresh(self, fetch: Callable[[], List[Dict]],
                      interval_s: float = 5.0):
        """Throttled quota refresh (the proxy passes a GCS fetcher);
        failures keep the last applied quotas."""
        now = self._clock()
        if now - self._refresh_t < interval_s:
            return
        self._refresh_t = now
        try:
            self.apply_quotas(fetch())
        except Exception:
            logger.debug("tenant quota refresh failed", exc_info=True)

    # -------------------------------------------------------- admission
    def _admissible_locked(self, tenant: str) -> bool:
        if self.total_limit > 0 and self._total >= self.total_limit:
            return False
        q = self.quota(tenant)
        return q <= 0 or self._inflight.get(tenant, 0) < q

    def _grant_locked(self, tenant: str):
        self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
        self._total += 1
        self.admitted_total[tenant] += 1

    def _flush_locked(self):
        """Grant every currently-serveable waiter, DRR order."""
        while True:
            nxt = self._drr.pop(eligible=self._admissible_locked)
            if nxt is None:
                return
            t, w = nxt
            if w.abandoned:
                continue
            self._grant_locked(t)
            w.granted = True
            w.event.set()

    def acquire(self, tenant: str = "", timeout_s: float = 30.0
                ) -> TenantLease:
        """Admit (possibly after queueing) or raise
        :class:`TenantQuotaExceeded`. Blocking — call from an executor
        thread, never an event loop."""
        tenant = tenant or "default"
        self._stamp_qps(tenant)
        with self._lock:
            # flush first so a newcomer never jumps waiters that freed
            # capacity has already earmarked
            self._flush_locked()
            if (self._admissible_locked(tenant)
                    and self._drr.queue_len(tenant) == 0):
                self._grant_locked(tenant)
                return TenantLease(self, tenant)
            if self._drr.queue_len(tenant) >= self.queue_max:
                return self._shed_locked(tenant)
            w = _Waiter()
            self._drr.push(tenant, w)
        if w.event.wait(timeout=timeout_s) and w.granted:
            return TenantLease(self, tenant)
        with self._lock:
            w.abandoned = True
            if w.granted:
                # granted while we were timing out: the slot is ours
                return TenantLease(self, tenant)
            return self._shed_locked(tenant)

    def _shed_locked(self, tenant: str) -> "TenantLease":
        self.shed_total[tenant] += 1
        self._ensure_metrics()
        if self._metrics is not None:
            self._metrics["shed"].inc(tags={"tenant": tenant})
        raise TenantQuotaExceeded(tenant, self._retry_after(tenant))

    def _retry_after(self, tenant: str) -> float:
        hint = self.retry_hint
        if hint is not None:
            try:
                w = hint(tenant)
                if w is not None and w > 0:
                    return float(w)
            except Exception:
                pass
        return cfg.tenant_retry_after_s

    def _release(self, tenant: str):
        with self._lock:
            n = self._inflight.get(tenant, 0)
            if n > 0:
                self._inflight[tenant] = n - 1
                self._total = max(0, self._total - 1)
            self._flush_locked()

    # ---------------------------------------------------------- metrics
    def _ensure_metrics(self):
        if self._metrics is not None:
            return
        try:
            from ray_tpu.util.metrics import Counter, Gauge
            self._metrics = {
                "qps": Gauge("serve_tenant_qps",
                             "offered requests/s per tenant "
                             "(5s sliding window)", tag_keys=("tenant",)),
                "shed": Counter("serve_tenant_shed_total",
                                "requests shed (429) per tenant",
                                tag_keys=("tenant",)),
            }
        except Exception:
            self._metrics = None

    def _stamp_qps(self, tenant: str):
        now = self._clock()
        with self._lock:
            win = self._qps.setdefault(tenant, collections.deque())
            win.append(now)
            cut = now - self.QPS_WINDOW_S
            while win and win[0] < cut:
                win.popleft()
            rate = len(win) / self.QPS_WINDOW_S
        self._ensure_metrics()
        if self._metrics is not None:
            self._metrics["qps"].set(rate, tags={"tenant": tenant})

    def stats(self) -> Dict:
        with self._lock:
            return {
                "inflight": {t: n for t, n in self._inflight.items() if n},
                "queued": {t: self._drr.queue_len(t)
                           for t in list(self._drr._q)},
                "admitted_total": dict(self.admitted_total),
                "shed_total": dict(self.shed_total),
                "quotas": dict(self._quota),
                "default_quota": self.default_quota,
            }


# ---------------------------------------------------- shared quota leases
class TenantTokenBucket:
    """One tenant's leased slice of the CLUSTER admission rate at one
    proxy (ROADMAP item 2a). Pure and clock-injectable: callers pass
    ``now`` explicitly, so the tier-1 suite drives refill arithmetic
    hermetically. ``rate <= 0`` means unlimited (untagged traffic stays
    zero-cost, mirroring the concurrency-quota convention)."""

    __slots__ = ("rate", "burst", "tokens", "_t")

    def __init__(self, rate: float, burst: float, now: float = 0.0):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self._t = float(now)

    def _refill(self, now: float):
        if now > self._t:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._t) * self.rate)
            self._t = now

    def take(self, now: float) -> bool:
        if self.rate <= 0:
            return True
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def wait_s(self, now: float) -> float:
        """Seconds until ONE token refills — the honest Retry-After.
        Every shed client sees a different deficit, so retries spread
        out instead of herding into a synchronized wave."""
        if self.rate <= 0:
            return 0.0
        self._refill(now)
        if self.tokens >= 1.0:
            return 0.0
        return (1.0 - self.tokens) / self.rate

    def set_params(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = min(self.tokens, self.burst)


class QuotaLeaseClient:
    """Proxy-side half of the GCS quota-lease protocol: N proxies
    enforce ONE cluster-wide fair-share policy instead of N independent
    views (ROADMAP item 2a).

    The GCS owns each tenant's cluster admission rate (``tenant_quotas``
    rows with ``rate``/``burst``, ``serve.set_tenant_quota``) and leases
    every proxy an equal proportional share; this client turns its share
    into local :class:`TenantTokenBucket` instances and renews on
    ``cfg.quota_lease_interval_s`` — pushing per-tenant burn deltas up
    (they aggregate into cluster totals for the edge bench and
    per-tenant SLO) and adopting re-split shares whenever the lease
    epoch moved (proxy join/leave/expire/revoke or a rate change).

    Failure discipline: a proxy whose lease is REVOKED — or that cannot
    renew for ``cfg.quota_lease_ttl_s`` — immediately degrades every
    bucket to ``cfg.quota_lease_conservative_frac`` of its last share
    and keeps trying to re-acquire. The GCS escrows the revoked share
    (it stays in the split denominator) until the lease TTLs out or
    re-acquires, so conservative local admission plus the survivors'
    shares can never sum past the cluster budget: zero over-admission
    by construction, which is exactly what the ``QuotaLeaseRevoker``
    chaos asserts. Thread-safe; ``call`` is a ``gcs_call``-like
    callable so tests inject a fake GCS."""

    def __init__(self, proxy_id: str, call: Callable[..., Any],
                 clock: Callable[[], float] = time.monotonic,
                 on_quotas: Optional[Callable[[List[Dict]], None]] = None):
        self.proxy_id = proxy_id
        self._call = call
        self._clock = clock
        self.on_quotas = on_quotas
        self._lock = threading.Lock()
        self._buckets: Dict[str, TenantTokenBucket] = {}
        self._shares: Dict[str, Dict] = {}
        self._epoch = 0
        self._revoked = False
        self._acquired = False
        self._renew_t = -1e18
        self._last_ok_t = -1e18
        self._burn: Dict[str, int] = collections.defaultdict(int)

    # ---------------------------------------------------------- protocol
    def acquire(self) -> bool:
        try:
            out = self._call("quota_lease_acquire", proxy_id=self.proxy_id)
        except Exception:
            logger.debug("quota lease acquire failed", exc_info=True)
            return False
        if not out:
            return False
        quotas = None
        with self._lock:
            quotas = self._apply_locked(out)
            self._revoked = False
            self._acquired = True
            self._last_ok_t = self._clock()
        if quotas is not None and self.on_quotas is not None:
            try:
                self.on_quotas(quotas)
            except Exception:
                pass
        return True

    def _apply_locked(self, out: Dict) -> Optional[List[Dict]]:
        """Adopt an acquire/renew response: epoch + re-split shares.
        Returns the piggybacked tenant_quotas rows, if any."""
        self._epoch = int(out.get("epoch", self._epoch))
        shares = out.get("shares")
        if shares is not None:
            self._shares = {t: dict(s) for t, s in shares.items()}
            now = self._clock()
            for t, s in shares.items():
                b = self._buckets.get(t)
                if b is None:
                    self._buckets[t] = TenantTokenBucket(
                        s["rate"], s["burst"], now=now)
                else:
                    b.set_params(s["rate"], s["burst"])
            for t in list(self._buckets):
                if t not in shares:
                    del self._buckets[t]
        return out.get("quotas")

    def _enter_degraded_locked(self):
        """Lease revoked or unrenewable: clamp every bucket to the
        conservative fraction of its LAST KNOWN share until re-lease."""
        if self._revoked:
            return
        self._revoked = True
        frac = cfg.quota_lease_conservative_frac
        for b in self._buckets.values():
            b.set_params(b.rate * frac, b.burst * frac)

    def maybe_renew(self, now: Optional[float] = None):
        """Throttled renew/re-acquire, called from the request path (and
        the probe loop) — no dedicated thread needed at the cadence."""
        now = self._clock() if now is None else now
        if now - self._renew_t < cfg.quota_lease_interval_s:
            return
        self._renew_t = now
        if not self._acquired or self._revoked:
            self.acquire()
            return
        with self._lock:
            burn, self._burn = dict(self._burn), collections.defaultdict(int)
        try:
            out = self._call("quota_lease_renew", proxy_id=self.proxy_id,
                             epoch=self._epoch, burn=burn)
        except Exception:
            logger.debug("quota lease renew failed", exc_info=True)
            with self._lock:
                # re-bank the deltas for the next successful push
                for t, n in burn.items():
                    self._burn[t] += n
                if now - self._last_ok_t > cfg.quota_lease_ttl_s:
                    self._enter_degraded_locked()
            return
        if out and out.get("revoked"):
            with self._lock:
                self._enter_degraded_locked()
            return
        quotas = None
        with self._lock:
            self._last_ok_t = now
            quotas = self._apply_locked(out or {})
        if quotas is not None and self.on_quotas is not None:
            try:
                self.on_quotas(quotas)
            except Exception:
                pass

    def release(self):
        try:
            self._call("quota_lease_release", proxy_id=self.proxy_id)
        except Exception:
            pass

    # --------------------------------------------------------- admission
    def admit(self, tenant: str, now: Optional[float] = None
              ) -> Optional[float]:
        """``None`` = admitted (one token burned); a float = shed, retry
        after that many seconds. Unrated tenants pass through — the
        concurrency quota in :class:`TenantAdmission` still applies."""
        now = self._clock() if now is None else now
        self.maybe_renew(now)
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                return None
            if b.take(now):
                self._burn[tenant] += 1
                return None
            return max(0.05, b.wait_s(now))

    def retry_hint(self, tenant: str) -> Optional[float]:
        """Wired into ``TenantAdmission.retry_hint`` so queue-full sheds
        also carry the honest refill deficit."""
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None or b.rate <= 0:
                return None
            return max(0.05, b.wait_s(self._clock()))

    @property
    def revoked(self) -> bool:
        return self._revoked

    def stats(self) -> Dict:
        with self._lock:
            return {
                "proxy_id": self.proxy_id,
                "epoch": self._epoch,
                "revoked": self._revoked,
                "shares": {t: dict(s) for t, s in self._shares.items()},
                "rates": {t: b.rate for t, b in self._buckets.items()},
                "pending_burn": dict(self._burn),
            }


# --------------------------------------------------------- fallback shed
_shed_metrics = None


def record_fallback_shed(deployment: str, fallback: str, app: str = ""):
    """Count one overflow request routed down the fallback ladder
    (handle-level; serve/handle.py calls this on every shed hop)."""
    global _shed_metrics
    if _shed_metrics is None:
        try:
            from ray_tpu.util.metrics import Counter
            _shed_metrics = Counter(
                "serve_fallback_shed_total",
                "requests shed to a fallback deployment",
                tag_keys=("deployment", "fallback"))
        except Exception:
            return
    _shed_metrics.inc(tags={"deployment": deployment, "fallback": fallback})
    from ray_tpu._private import events
    events.record_instant("serve.fallback_shed", category="serve",
                          app=app, deployment=deployment, fallback=fallback)


# ------------------------------------------------------------ shell pool
class ShellPool:
    """A small shared pool of pre-warmed :class:`ReplicaShell` actors.
    ``ensure()`` (reconcile-loop tick, off the controller lock) tops the
    pool up; ``checkout()`` hands a shell to a revival; a shell that
    fails its attach is ``discard()``-ed (killed), never returned."""

    def __init__(self, spawn: Callable[[], Any],
                 size: Optional[int] = None):
        self._spawn = spawn
        self.size = int(cfg.fleet_shell_pool_size if size is None else size)
        self._idle: List[Any] = []
        self._lock = threading.Lock()
        self._filling = threading.Lock()
        self.spawned_total = 0
        self.checked_out_total = 0
        self.discarded_total = 0

    def ensure(self):
        """Replenish to the target size. Single-flight; spawn failures
        log and stop the pass (the next tick retries)."""
        if not self._filling.acquire(blocking=False):
            return
        try:
            while True:
                with self._lock:
                    if len(self._idle) >= self.size:
                        return
                try:
                    shell = self._spawn()
                except Exception:
                    logger.warning("shell spawn failed (next tick retries)",
                                   exc_info=True)
                    return
                with self._lock:
                    self._idle.append(shell)
                    self.spawned_total += 1
        finally:
            self._filling.release()

    def checkout(self) -> Optional[Any]:
        with self._lock:
            if not self._idle:
                return None
            self.checked_out_total += 1
            return self._idle.pop(0)    # FIFO: oldest (warmest) first

    def checkout_many(self, n: int) -> Optional[List[Any]]:
        """Atomic gang checkout: n shells or none. A partial gang is
        useless (every rank of a sharded replica must come up together)
        and handing out half the pool would starve the next single-shell
        revival for nothing."""
        with self._lock:
            if len(self._idle) < n:
                return None
            self.checked_out_total += n
            out, self._idle = self._idle[:n], self._idle[n:]
            return out

    def discard(self, shell: Any):
        """A shell that failed mid-attach is in an unknown state: kill
        it rather than pool it."""
        with self._lock:
            self.discarded_total += 1
        try:
            import ray_tpu
            ray_tpu.kill(shell)
        except Exception:
            logger.debug("shell kill failed", exc_info=True)

    def idle(self) -> int:
        with self._lock:
            return len(self._idle)

    def stats(self) -> Dict:
        with self._lock:
            return {"idle": len(self._idle), "target": self.size,
                    "spawned_total": self.spawned_total,
                    "checked_out_total": self.checked_out_total,
                    "discarded_total": self.discarded_total}


class ReplicaShell:
    """A pre-warmed replica actor with no deployment attached yet: the
    process exists, the heavy imports (jax/numpy/msgpack) are paid, and
    the actor is sitting warm in the :class:`ShellPool`. ``attach()``
    turns it into an ordinary :class:`~ray_tpu.serve.replica.Replica`
    for one deployment — constructing the callable inside the warm
    process (an LLM's weights load here, e.g. from the PR 11 arena via
    its ``params_fn``) and running the callable's optional
    ``on_shell_attach()`` hook (LLMDeployment warms its compiled
    programs) BEFORE the controller publishes the replica to routing
    tables, so held requests never pay import or compile latency.

    Chaos: ``RAY_TPU_TESTING_RPC_FAILURE="shell_attach=p"``
    (:class:`~ray_tpu.util.chaos.ShellAttachKiller`) fires at attach
    entry and again after construction, pre-ready — the fleet manager
    must discard this shell and route the held requests through a fresh
    shell or a cold replica, exactly once."""

    def __init__(self):
        from ray_tpu.serve.replica import Replica
        self._replica_cls = Replica
        Replica._init_state(self)
        self._attached = False
        self._shard = None      # set by attach_shard (gang revival)
        self._prewarm()

    def _prewarm(self):
        try:
            import msgpack  # noqa: F401
            import numpy  # noqa: F401
            import jax  # noqa: F401
        except Exception:
            logger.debug("shell prewarm import failed", exc_info=True)

    def attach(self, serialized_callable: bytes, init_args: tuple,
               init_kwargs: Dict, is_function: bool) -> bool:
        from ray_tpu._private import events, rpc
        rpc._maybe_inject_failure("shell_attach")
        # launch attribution: callable construction and compile warmup
        # chain under the revival's replica.launch trace (the task ctx
        # propagated with the attach call)
        t0 = time.time()
        self._replica_cls._init_callable(
            self, serialized_callable, tuple(init_args), init_kwargs,
            is_function)
        t1 = time.time()
        events.record_complete("launch.shell_attach", t0, t1,
                               category="launch")
        hook = getattr(self._callable, "on_shell_attach", None)
        if hook is not None:
            hook()
            events.record_complete("launch.warmup", t1, time.time(),
                                   category="launch")
        rpc._maybe_inject_failure("shell_attach")
        self._attached = True
        return True

    def attach_shard(self, rank: int, world_size: int, group_name: str,
                     serialized_callable: bytes, init_args: tuple,
                     init_kwargs: Dict, is_function: bool) -> bool:
        """Gang-aware attach: turn this warm shell into ONE RANK of a
        sharded replica group (serve/sharded_replica.py). The fleet
        manager checks out ``world_size`` shells atomically and runs
        this on all of them CONCURRENTLY — setup_distributed's
        rendezvous and the callable's lockstep ``on_shell_attach``
        warmup both need every rank in flight at once. Chaos fires at
        the same two points as a plain attach; one rank failing
        discards the whole gang (partial gangs are never published)."""
        from ray_tpu._private import events, rpc
        from ray_tpu.serve.sharded_replica import ReplicaShard
        rpc._maybe_inject_failure("shell_attach")
        t0 = time.time()
        shard = ReplicaShard(rank, world_size)
        shard.setup_distributed(group_name)
        shard.init_callable(serialized_callable, tuple(init_args),
                            init_kwargs, is_function)
        t1 = time.time()
        events.record_complete("launch.shell_attach", t0, t1,
                               category="launch", rank=rank)
        hook = getattr(shard._callable, "on_shell_attach", None)
        if hook is not None:
            hook()
            events.record_complete("launch.warmup", t1, time.time(),
                                   category="launch", rank=rank)
        rpc._maybe_inject_failure("shell_attach")
        self._shard = shard
        self._attached = True
        return True

    def _require_attached(self):
        if not self._attached:
            raise RuntimeError("replica shell has no deployment attached")

    # ------------------------------------------------- replica protocol
    def handle_request(self, method, args, kwargs):
        self._require_attached()
        if self._shard is not None:
            return self._shard.handle_request(method, args, kwargs)
        return self._replica_cls.handle_request(self, method, args, kwargs)

    def handle_stream(self, method, args, kwargs):
        self._require_attached()
        if self._shard is not None:
            yield from self._shard.handle_stream(method, args, kwargs)
            return
        yield from self._replica_cls.handle_stream(self, method, args,
                                                   kwargs)

    def begin_drain(self):
        if self._shard is not None:
            return self._shard.begin_drain()
        return self._replica_cls.begin_drain(self)

    def get_runtime_state(self):
        if self._shard is not None:
            return self._shard.get_runtime_state()
        return self._replica_cls.get_runtime_state(self)

    def get_queue_len(self):
        if self._shard is not None:
            return self._shard.get_queue_len()
        return self._replica_cls.get_queue_len(self)

    def check_health(self):
        # an idle pooled shell is healthy by construction
        if not self._attached:
            return True
        if self._shard is not None:
            return self._shard.check_health()
        return self._replica_cls.check_health(self)

    def reconfigure(self, user_config):
        self._require_attached()
        if self._shard is not None:
            return self._shard.reconfigure(user_config)
        return self._replica_cls.reconfigure(self, user_config)

    # ------------------------------------- shard protocol (gang peers)
    # Rank 0's ReplicaShard fans to peer handles by these names — when
    # the gang was revived from pooled shells, the peers ARE shells.
    def set_peers(self, peers):
        self._require_attached()
        return self._shard.set_peers(peers)

    def run_shard(self, method, args, kwargs):
        self._require_attached()
        return self._shard.run_shard(method, args, kwargs)

    def run_shard_drain(self, method, args, kwargs):
        self._require_attached()
        return self._shard.run_shard_drain(method, args, kwargs)

    def check_peer_health(self):
        self._require_attached()
        return self._shard.check_peer_health()

    def reconfigure_shard(self, user_config):
        self._require_attached()
        return self._shard.reconfigure_shard(user_config)


# ---------------------------------------------------------- fleet manager
class FleetManager:
    """Controller-side fleet brain: idle reaping, shell-pool upkeep, and
    revival. One instance per :class:`ServeController`, created lazily
    when the first deployment opts into scale-to-zero.

    Lock discipline mirrors the controller's: ``note_load`` runs under
    the controller lock (pure bookkeeping); revivals run on their own
    thread and take the lock only for the quick attach/publish
    mutation, so a slow weight load never stalls reconcile."""

    COLD_HIST_MS = [50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                    10000.0, 30000.0, 60000.0]

    def __init__(self, controller, spawn_shell: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._c = controller
        self._clock = clock
        self._lock = threading.Lock()
        self._idle_since: Dict[tuple, float] = {}
        self._reviving: set = set()
        self._cold_ms: Dict[tuple, List[float]] = {}
        self._hist = None
        self.pool = ShellPool(spawn_shell or self._spawn_shell)
        self.revivals_total = 0
        self.cold_builds_total = 0     # revivals that fell back past pool

    # ----------------------------------------------------- idle reaping
    def note_load(self, app: str, name: str, dep: Dict,
                  total_load: float, now: Optional[float] = None) -> bool:
        """One reconcile tick's idle-reaper step for one deployment.
        Caller holds the controller lock. Returns True when the
        deployment was scaled to zero THIS tick."""
        key = (app, name)
        auto = dep["spec"]["config"].get("autoscaling_config")
        now = self._clock() if now is None else now
        with self._lock:
            reviving = key in self._reviving
        zero, idle_since = decide_scale_to_zero(
            auto, self._idle_since.get(key), now, dep["target"],
            total_load, reviving)
        if idle_since is None:
            self._idle_since.pop(key, None)
        else:
            self._idle_since[key] = idle_since
        if not zero or dep["target"] == 0:
            return False
        dep["target"] = 0
        self._idle_since.pop(key, None)
        from ray_tpu._private import events
        events.record_instant(
            "serve.scale_to_zero", category="serve", app=app,
            deployment=name,
            idle_s=round(now - (idle_since or now), 3))
        logger.info("scale-to-zero: %s/%s idle past %ss", app, name,
                    (auto or {}).get("idle_scale_to_zero_s"))
        return True

    # ------------------------------------------------------------- tick
    def tick(self, want_shells: bool):
        """Reconcile-loop hook (off the controller lock): keep the shell
        pool topped up while any deployment can scale to zero."""
        if want_shells:
            self.pool.ensure()

    def _spawn_shell(self):
        import ray_tpu
        actor_cls = ray_tpu.remote(ReplicaShell)
        return actor_cls.options(max_concurrency=18,
                                 num_cpus=0.1).remote()

    # ---------------------------------------------------------- revival
    def revive(self, app: str, name: str) -> bool:
        """Router-requested cold start. Idempotent: concurrent requests
        for one deployment fold into a single revival; a deployment
        that already has replicas (or one building) returns True
        immediately — the caller keeps polling the routing table."""
        key = (app, name)
        with self._c._lock:
            dep = self._c.apps.get(app, {}).get(name)
            if dep is None:
                return False
            if dep["replicas"]:
                return True
            if dep.get("_creating"):
                return True    # a build is already in flight; poll on
            with self._lock:
                if key in self._reviving:
                    return True
                self._reviving.add(key)
            if dep["target"] < 1:
                dep["target"] = 1
            dep["_creating"] = True        # reconcile must not double-build
            self._idle_since.pop(key, None)
        threading.Thread(target=self._revive_thread, args=(key, dep),
                         name=f"fleet-revive-{name}", daemon=True).start()
        return True

    def _revive_thread(self, key: tuple, dep: Dict):
        import ray_tpu
        t0 = self._clock()
        app, name = key
        try:
            with self._c._lock:
                spec = dep["spec"]
                gen = dep.get("gen", 0)
            handle, group, via = None, None, "shell"
            n_hosts = int(spec["config"].get("num_hosts") or 1)
            if n_hosts > 1:
                got = self._attach_shard_gang(spec, n_hosts)
                if got is not None:
                    handle, group = got
            else:
                # try every pooled shell once, then one fresh cold
                # build — the chaos suite kills shells mid-attach and
                # the held requests must still land exactly once
                from ray_tpu._private import events
                for attempt in range(max(1, self.pool.size)):
                    t_co = time.time()
                    shell = self.pool.checkout()
                    if shell is None:
                        break
                    events.record_complete(
                        "launch.shell_checkout", t_co, time.time(),
                        category="launch", app=app, deployment=name)
                    try:
                        ray_tpu.get(shell.attach.remote(
                            spec["callable"], tuple(spec["init_args"]),
                            spec["init_kwargs"], spec["is_function"]),
                            timeout=cfg.fleet_attach_timeout_s)
                        handle = shell
                        break
                    except Exception:
                        logger.warning(
                            "shell attach failed for %s/%s (attempt %d); "
                            "discarding shell", app, name, attempt + 1,
                            exc_info=True)
                        self.pool.discard(shell)
            if handle is None:
                via = "cold"
                self.cold_builds_total += 1
                handle, group = self._c._build_replica(spec)
            cold_ms = (self._clock() - t0) * 1e3
            with self._c._lock:
                alive = (self._c.apps.get(spec.get("app_name") or "", {})
                         .get(spec["name"]) is dep)
                stale = dep.get("gen", 0) != gen
                if alive and not stale:
                    dep["replicas"].append(handle)
                    dep.setdefault("replica_gens", []).append(gen)
                    if group is not None:
                        dep.setdefault("groups", {})[
                            handle._actor_id] = group
                    dep["version"] += 1
                    self._c._bump_dep(dep)
            if not alive or stale:
                try:
                    ray_tpu.kill(handle)
                except Exception:
                    pass
                return
            self.revivals_total += 1
            self._record_cold_start(key, cold_ms, via)
        except Exception:
            logger.exception("revival failed for %s/%s (reconcile "
                             "retries the build)", app, name)
        finally:
            with self._c._lock:
                dep["_creating"] = False
            with self._lock:
                self._reviving.discard(key)
            try:
                self.pool.ensure()     # replenish for the next cold start
            except Exception:
                logger.debug("shell pool refill failed", exc_info=True)

    def _attach_shard_gang(self, spec: Dict, n_hosts: int):
        """Gang-aware pre-warm revival for a sharded (``num_hosts > 1``)
        deployment: check out ``n_hosts`` shells atomically and attach
        them CONCURRENTLY as the ranks of one replica group —
        rendezvous + lockstep warmup need every rank in flight at once
        (ReplicaShell.attach_shard). Returns ``(rank0_handle,
        group_record)`` or None (pool too shallow / attach failed /
        topology-pinned spec) — the caller cold-builds via the
        controller's gang path.

        Topology-pinned gangs always cold-build: pooled shells carry no
        placement, so they cannot satisfy STRICT_SPREAD over one
        slice's hosts."""
        import uuid

        import ray_tpu
        if spec["config"].get("topology"):
            return None
        shells = self.pool.checkout_many(n_hosts)
        if shells is None:
            return None
        group_name = f"serve-shard-{uuid.uuid4().hex[:8]}"
        try:
            ray_tpu.get(
                [s.attach_shard.remote(
                    rank, n_hosts, group_name, spec["callable"],
                    tuple(spec["init_args"]), spec["init_kwargs"],
                    spec["is_function"])
                 for rank, s in enumerate(shells)],
                timeout=cfg.fleet_attach_timeout_s)
            ray_tpu.get(shells[0].set_peers.remote(shells[1:]), timeout=60)
        except Exception:
            logger.warning(
                "gang shell attach failed for %s (%d ranks); discarding "
                "the whole gang", spec["name"], n_hosts, exc_info=True)
            for s in shells:
                self.pool.discard(s)
            return None
        return shells[0], {"members": list(shells), "pg": None}

    def _record_cold_start(self, key: tuple, cold_ms: float, via: str):
        with self._lock:
            samples = self._cold_ms.setdefault(key, [])
            samples.append(cold_ms)
            del samples[:-256]
        if self._hist is None:
            try:
                from ray_tpu.util.metrics import Histogram
                self._hist = Histogram(
                    "serve_cold_start_ms",
                    "scale-to-zero revival latency (request hold -> "
                    "replica published)", boundaries=self.COLD_HIST_MS)
            except Exception:
                self._hist = False
        if self._hist:
            self._hist.observe(cold_ms)
        from ray_tpu._private import events
        events.record_instant(
            "serve.cold_start", category="serve", app=key[0],
            deployment=key[1], cold_start_ms=round(cold_ms, 1), via=via)
        logger.info("cold start %s/%s via %s in %.0fms", key[0], key[1],
                    via, cold_ms)

    # ------------------------------------------------------------ status
    def cold_start_stats(self) -> Dict[str, Dict]:
        out = {}
        with self._lock:
            for (app, name), samples in self._cold_ms.items():
                if not samples:
                    continue
                s = sorted(samples)
                out[f"{app}/{name}"] = {
                    "count": len(s),
                    "last_ms": round(samples[-1], 1),
                    "p50_ms": round(_pctl(s, 0.50), 1),
                    "p99_ms": round(_pctl(s, 0.99), 1),
                }
        return out

    def status(self) -> Dict:
        with self._lock:
            reviving = [f"{a}/{n}" for a, n in self._reviving]
            idle = {f"{a}/{n}": round(self._clock() - t, 1)
                    for (a, n), t in self._idle_since.items()}
        return {"shell_pool": self.pool.stats(),
                "revivals_total": self.revivals_total,
                "cold_builds_total": self.cold_builds_total,
                "reviving": reviving, "idle_s": idle,
                "cold_starts": self.cold_start_stats()}


def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]
