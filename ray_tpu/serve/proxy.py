"""HTTP ingress proxy actor (reference: python/ray/serve/_private/proxy.py
HTTPProxy :779 — uvicorn/ASGI there; aiohttp here, same role: terminate
HTTP, route by prefix, forward to the ingress deployment handle)."""

from __future__ import annotations

import asyncio
import json
from typing import Dict


class HttpProxy:
    def __init__(self, port: int, routes: Dict[str, str],
                 ingress: Dict[str, str]):
        self.port = port
        self.routes = routes          # route_prefix -> app_name
        self.ingress = ingress        # app_name -> deployment name
        self._handles = {}
        self._ready = False
        from ray_tpu._private.worker import global_worker
        asyncio.run_coroutine_threadsafe(
            self._start(), global_worker.core.loop).result(timeout=30)

    async def _start(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "0.0.0.0", self.port)
        await site.start()
        self._ready = True

    def ready(self):
        return self._ready

    def update_routes(self, routes: Dict[str, str],
                      ingress: Dict[str, str]):
        self.routes = routes
        self.ingress = ingress
        return True

    def _handle_for(self, app_name: str):
        h = self._handles.get(app_name)
        if h is None:
            from ray_tpu.serve.handle import DeploymentHandle
            h = DeploymentHandle(self.ingress[app_name], app_name)
            self._handles[app_name] = h
        return h

    async def _handle(self, request):
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        app_name = None
        for prefix, name in sorted(self.routes.items(),
                                   key=lambda kv: -len(kv[0])):
            if path.startswith(prefix):
                app_name = name
                break
        if app_name is None:
            return web.Response(status=404, text="no route")
        if request.content_type == "application/json":
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                payload = await request.text()
        else:
            payload = await request.text()
        handle = self._handle_for(app_name)
        loop = asyncio.get_event_loop()
        try:
            # routing + submit use the sync API; keep them off this loop
            result = await loop.run_in_executor(
                None, lambda: handle.remote(payload).result(timeout=60))
        except Exception as e:
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        if isinstance(result, (dict, list)):
            return web.json_response(result)
        return web.Response(text=str(result))
