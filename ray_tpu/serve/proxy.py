"""HTTP ingress proxy actor, one per node (reference:
python/ray/serve/_private/proxy.py HTTPProxy :779 — uvicorn/ASGI there;
aiohttp here, same role: terminate HTTP, route by prefix, forward to the
ingress deployment handle). Routing state arrives by long-poll push from
the controller (reference: LongPollClient, _private/long_poll.py:64), so
a config change is visible here within one notify, not a poll interval.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Dict, Optional


class HttpProxy:
    def __init__(self, port: int, controller):
        self.port = port
        self.controller = controller
        self.routes: Dict[str, str] = {}      # route_prefix -> app_name
        self.ingress: Dict[str, str] = {}     # app_name -> deployment
        self._versions = {"routes": 0}
        self._handles = {}
        self._adm = None                       # lazy TenantAdmission
        self._lease = None                     # lazy QuotaLeaseClient
        self._ttft_hist = None                 # lazy per-tenant TTFT
        self._addr: Optional[str] = None
        from ray_tpu._private.worker import global_worker
        asyncio.run_coroutine_threadsafe(
            self._start(), global_worker.core.loop).result(timeout=30)
        self._prime_routes()
        self._poller = threading.Thread(target=self._longpoll_loop,
                                        daemon=True)
        self._poller.start()

    def _prime_routes(self):
        from ray_tpu.serve.long_poll import prime_snapshot
        prime_snapshot(self.controller, self._versions, self._on_update)

    async def _start(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app)
        await runner.setup()
        try:
            site = web.TCPSite(runner, "0.0.0.0", self.port)
            await site.start()
            bound = self.port
        except OSError:
            # port taken (several proxies share a host in tests / when
            # multiple nodes run on one machine): fall back to ephemeral
            site = web.TCPSite(runner, "0.0.0.0", 0)
            await site.start()
            bound = site._server.sockets[0].getsockname()[1]
        from ray_tpu._private.rpc import node_ip_address
        self._addr = f"{node_ip_address()}:{bound}"

    def _longpoll_loop(self):
        from ray_tpu.serve.long_poll import run_longpoll_loop
        run_longpoll_loop(lambda: self.controller, self._versions,
                          self._on_update)

    def _on_update(self, key: str, data):
        if key != "routes":
            return
        self.routes = data["routes"]
        new_ingress = data["ingress"]
        # drop cached handles whose app's ingress deployment changed —
        # a stale handle would keep routing to the old deployment
        for app, dep in list(self._handles.items()):
            if new_ingress.get(app) != dep.deployment_name:
                self._handles.pop(app, None)
        self.ingress = new_ingress

    def ready(self) -> str:
        return self._addr

    def admission_stats(self) -> Dict:
        """Admission + lease state for probes/tests (reports/edge_probe
        asserts zero over-admission across proxies from these)."""
        out = {"admission": None, "lease": None}
        if self._adm is not None:
            out["admission"] = self._adm.stats()
        if self._lease is not None:
            out["lease"] = self._lease.stats()
        return out

    def _handle_for(self, app_name: str):
        h = self._handles.get(app_name)
        if h is None:
            from ray_tpu.serve.handle import DeploymentHandle
            h = DeploymentHandle(self.ingress[app_name], app_name)
            self._handles[app_name] = h
        return h

    # ------------------------------------------------- tenant admission
    def _admission(self):
        if self._adm is None:
            from ray_tpu.serve.fleet import TenantAdmission
            self._adm = TenantAdmission()
            lease = self._lease_client()
            if lease is not None:
                self._adm.retry_hint = lease.retry_hint
        return self._adm

    def _lease_client(self):
        """Lazy QuotaLeaseClient (serve/fleet.py): this proxy's share of
        every tenant's CLUSTER admission rate, leased from the GCS so N
        proxies enforce one fair-share policy. None when the worker is
        not connected (hermetic tests drive TenantAdmission directly)."""
        if self._lease is None:
            try:
                import ray_tpu
                from ray_tpu.serve.fleet import QuotaLeaseClient
                w = ray_tpu._get_worker()
                ctx = ray_tpu.get_runtime_context()
                pid = str(ctx.get("actor_id") or f"proxy:{id(self):x}")
                self._lease = QuotaLeaseClient(
                    pid, w.gcs_call,
                    on_quotas=lambda rows: self._adm.apply_quotas(rows)
                    if self._adm is not None else None)
                self._lease.acquire()
            except Exception:
                return None
        return self._lease

    @staticmethod
    def _fetch_quotas():
        import ray_tpu
        return ray_tpu._get_worker().gcs_call("get_tenant_quotas")

    @staticmethod
    def _tenant_of(request, payload) -> str:
        """X-RayTPU-Tenant header, falling back to a `tenant` field in a
        JSON payload (forwarded untouched either way)."""
        t = request.headers.get("X-RayTPU-Tenant", "")
        if not t and isinstance(payload, dict):
            t = str(payload.get("tenant") or "")
        return t

    def _acquire_tenant(self, tenant: str):
        """Blocking fair-share admission (serve/fleet.py): runs on an
        executor thread, never this event loop. Raises
        TenantQuotaExceeded for over-quota work — mapped to 429 +
        Retry-After by the caller. Two gates in order: this proxy's
        leased share of the tenant's CLUSTER rate (token bucket, the
        cheap check), then the local concurrency quota + DRR queue."""
        adm = self._admission()
        lease = self._lease_client()
        if lease is not None and tenant:
            wait = lease.admit(tenant)
            if wait is not None:
                from ray_tpu.serve.fleet import TenantQuotaExceeded
                adm.shed_total[tenant] += 1
                raise TenantQuotaExceeded(tenant, wait)
        adm.maybe_refresh(self._fetch_quotas)
        return adm.acquire(tenant)

    @staticmethod
    def _shed_response(e):
        from aiohttp import web
        # sub-second precision: the refill-deficit hint loses its
        # de-herding value if every response rounds up to the same
        # integer second
        retry = max(0.05, float(e.retry_after_s))
        return web.Response(
            status=429,
            text=f"tenant {e.tenant!r} over quota",
            headers={"Retry-After": f"{retry:.3f}"})

    def _record_ttft(self, tenant: str, dt_s: float):
        """Per-tenant time-to-first-byte as THIS tenant experienced it
        at the ingress (queueing + routing + prefill included) — the
        observation series the per-tenant SLO burn rows (serve/slo.py
        evaluate_tenant_slo) are evaluated against."""
        try:
            if self._ttft_hist is None:
                from ray_tpu.util.metrics import Histogram
                self._ttft_hist = Histogram(
                    "serve_tenant_ttft_ms",
                    "ingress-observed time to first byte per tenant",
                    boundaries=[1.0, 5.0, 25.0, 100.0, 500.0, 2000.0],
                    tag_keys=("tenant",))
            self._ttft_hist.observe(dt_s * 1000.0,
                                    tags={"tenant": tenant or "default"})
        except Exception:
            pass

    @staticmethod
    def _incoming_trace(request):
        """W3C traceparent (`00-<trace32>-<span16>-<flags>`): an
        upstream client's trace continues through the proxy instead of
        rooting a fresh one."""
        parts = request.headers.get("traceparent", "").split("-")
        if len(parts) == 4 and len(parts[1]) == 32 and len(parts[2]) == 16:
            return parts[1], parts[2]
        return None, None

    async def _handle(self, request):
        from aiohttp import web

        from ray_tpu._private import events

        path = "/" + request.match_info["tail"]
        app_name = None
        for prefix, name in sorted(self.routes.items(),
                                   key=lambda kv: -len(kv[0])):
            if path.startswith(prefix):
                app_name = name
                break
        if app_name is None:
            return web.Response(status=404, text="no route")
        if request.content_type == "application/json":
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                payload = await request.text()
        else:
            payload = await request.text()
        handle = self._handle_for(app_name)
        # session affinity: an explicit header (or a session_id field in
        # a JSON payload) pins this request's routing to the replica the
        # session hashes to — repeat prompts land where their prefix KV
        # is cached (the payload is forwarded untouched)
        session_id = request.headers.get("X-RayTPU-Session", "")
        if not session_id and isinstance(payload, dict):
            session_id = str(payload.get("session_id") or "")
        if session_id:
            handle = handle.options(session_id=session_id)
        # per-tenant fair-share admission (serve/fleet.py): DRR queueing
        # under concurrency quotas, over-quota work shed with 429 +
        # Retry-After BEFORE it can collapse the replica queues. The
        # blocking acquire runs on an executor thread.
        loop = asyncio.get_event_loop()
        tenant = self._tenant_of(request, payload)
        from ray_tpu.serve.fleet import TenantQuotaExceeded
        try:
            lease = await loop.run_in_executor(
                None, self._acquire_tenant, tenant)
        except TenantQuotaExceeded as e:
            return self._shed_response(e)
        if tenant:
            handle = handle.options(tenant=tenant)
        # the request's root span: every downstream phase (replica task,
        # engine slot, first token) parents under it because the handle
        # call below submits inside its trace context
        trace_id, parent = self._incoming_trace(request)
        span = events.start_span("proxy.request", category="serve",
                                 trace_id=trace_id, parent_span_id=parent,
                                 method=request.method, path=path,
                                 app=app_name, tenant=tenant or None)
        t0 = time.monotonic()
        if (request.headers.get("X-RayTPU-Stream") == "1"
                or "text/event-stream" in request.headers.get("Accept", "")):
            try:
                return await self._handle_streaming(request, handle,
                                                    payload, span,
                                                    tenant=tenant, t0=t0)
            finally:
                lease.release()

        def _call():
            # routing + submit use the sync API; keep them off this loop.
            # trace_context makes the replica task a child of this span.
            with events.trace_context(span.trace_id, span.span_id):
                return handle.remote(payload).result(timeout=60)

        try:
            result = await loop.run_in_executor(None, _call)
        except Exception as e:
            span.end(status=500, error=type(e).__name__)
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        finally:
            lease.release()
        self._record_ttft(tenant, time.monotonic() - t0)
        span.end(status=200)
        if isinstance(result, (dict, list)):
            return web.json_response(result)
        return web.Response(text=str(result))

    async def _handle_streaming(self, request, handle, payload, span,
                                tenant: str = "",
                                t0: Optional[float] = None):
        """Streaming ingress: drive the deployment's streaming handle on
        an executor thread and relay each chunk as one NDJSON line. A
        client that disconnects mid-stream closes the replica-side
        generator (its finally runs — engine slots free immediately)."""
        import threading

        from aiohttp import web

        from ray_tpu._private import events

        loop = asyncio.get_event_loop()
        q: asyncio.Queue = asyncio.Queue()
        cancelled = threading.Event()

        def _produce():
            gen = None
            try:
                with events.trace_context(span.trace_id, span.span_id):
                    gen = handle.options(stream=True).remote(payload)
                n = 0
                # frame-granular drain: next_batch() hands back every
                # item already buffered from one coalesced wire frame,
                # so the writer emits a frame's NDJSON lines in ONE
                # write instead of a syscall per token
                while True:
                    try:
                        batch = gen.next_batch()
                    except StopIteration:
                        break
                    if cancelled.is_set():
                        gen.close()
                        loop.call_soon_threadsafe(q.put_nowait,
                                                  ("end", n))
                        return
                    loop.call_soon_threadsafe(q.put_nowait,
                                              ("batch", batch))
                    n += len(batch)
                loop.call_soon_threadsafe(q.put_nowait, ("end", n))
            except Exception as e:
                if gen is not None:
                    try:
                        gen.close()
                    except Exception:
                        pass
                loop.call_soon_threadsafe(q.put_nowait, ("error", e))

        resp = web.StreamResponse()
        resp.content_type = "application/x-ndjson"
        await resp.prepare(request)
        producer = loop.run_in_executor(None, _produce)
        try:
            first = True
            while True:
                kind, item = await q.get()
                if kind == "batch":
                    if first and t0 is not None:
                        self._record_ttft(tenant, time.monotonic() - t0)
                        first = False
                    # one write per coalesced frame, one NDJSON line per
                    # item — the client-visible protocol is unchanged
                    await resp.write("".join(
                        json.dumps(v, default=str) + "\n"
                        for v in item).encode())
                elif kind == "error":
                    span.end(status=500, error=type(item).__name__)
                    await resp.write(
                        (json.dumps({"error": f"{type(item).__name__}: "
                                              f"{item}"}) + "\n").encode())
                    break
                else:
                    span.end(status=200, chunks=item)
                    break
        except (ConnectionResetError, ConnectionError):
            cancelled.set()
            span.end(status=499, error="client_disconnected")
        finally:
            cancelled.set()
            await producer
        try:
            await resp.write_eof()
        except (ConnectionResetError, ConnectionError):
            pass
        return resp
