"""SLO burn-rate engine for Serve deployments.

Deployments declare objectives (``SloConfig``: a latency objective —
"at most ``budget_fraction`` of requests may exceed ``threshold_ms`` on
``latency_metric``", i.e. *p95 TTFT ≤ X ms* with the default 5% budget
— and/or an error-rate objective). The controller evaluates them every
reconcile tick against the GCS time-series plane and publishes:

- ``slo_burn_rate`` gauges (tags: app, deployment, objective, window) —
  burn rate 1.0 means the error budget is being consumed exactly at the
  allowed pace; 2.0 means twice as fast;
- ``slo_violating`` gauges (0/1);
- ``slo.violation`` / ``slo.recovered`` flight-recorder instants on
  state transitions, so outages line up with the spans that caused them
  on the unified timeline.

Violation uses the standard multi-window burn-rate rule (Google
SRE-workbook shape): alert only when BOTH the fast window (reacts
quickly, noisy alone) and the slow window (confirms it is sustained)
burn above threshold. This is precisely the input signal ROADMAP item
2's autoscaling loop needs — scale on sustained burn, not on instant
spikes.

The evaluation core is pure (``evaluate_slo`` takes a query callable)
so tier-1 tests drive it against a synthetic time-series plane with no
cluster. ``SloTracker`` adds the transition memory + metric/event
emission used by the controller.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class SloConfig:
    """Objectives for one deployment. Thresholds default to None =
    objective disabled.

    p95_ttft_ms is sugar for the common case: a latency objective with
    threshold = that value and budget_fraction = 0.05 on latency_metric.
    """
    # latency objective: fraction of observations on `latency_metric`
    # above `threshold_ms` must stay below `budget_fraction`
    p95_ttft_ms: Optional[float] = None
    latency_metric: str = "serve_llm_ttft_ms"
    threshold_ms: Optional[float] = None
    budget_fraction: float = 0.05
    # error-rate objective: rate(error_metric+error_tags) /
    # rate(total_metric) must stay below max_error_rate
    max_error_rate: Optional[float] = None
    error_metric: str = "serve_llm_requests_total"
    error_tags: Optional[Dict[str, str]] = None
    total_metric: str = "serve_llm_requests_total"
    # burn-rate windows: violate only when BOTH burn above threshold
    fast_window_s: float = 30.0
    slow_window_s: float = 120.0
    burn_threshold: float = 1.0


def _cfg_get(slo, key, default=None):
    """SloConfig or plain dict (specs cross the wire as dicts)."""
    if isinstance(slo, dict):
        v = slo.get(key, default)
        return default if v is None and default is not None else v
    return getattr(slo, key, default)


def evaluate_slo(slo, query: Callable[..., Dict]) -> List[Dict]:
    """Evaluate every enabled objective. `query(name, window, agg,
    tags=None, threshold=None)` must return the GCS query_metrics shape
    ({"value": ...}). Returns one row per objective:
    {objective, target, burn_fast, burn_slow, violating, windows}.
    A window with no samples contributes burn 0 (no traffic = no budget
    spend), the Prometheus absent-metric convention."""
    out: List[Dict] = []
    fast_w = float(_cfg_get(slo, "fast_window_s", 30.0) or 30.0)
    slow_w = float(_cfg_get(slo, "slow_window_s", 120.0) or 120.0)
    burn_thr = float(_cfg_get(slo, "burn_threshold", 1.0) or 1.0)

    threshold = _cfg_get(slo, "threshold_ms")
    if threshold is None:
        threshold = _cfg_get(slo, "p95_ttft_ms")
    if threshold is not None:
        budget = float(_cfg_get(slo, "budget_fraction", 0.05) or 0.05)
        metric = _cfg_get(slo, "latency_metric", "serve_llm_ttft_ms")
        burns = {}
        for label, w in (("fast", fast_w), ("slow", slow_w)):
            frac = query(metric, window=w, agg="frac_over",
                         threshold=float(threshold)).get("value")
            burns[label] = (frac or 0.0) / budget
        out.append({
            "objective": "latency", "metric": metric,
            "target": float(threshold), "budget_fraction": budget,
            "burn_fast": round(burns["fast"], 4),
            "burn_slow": round(burns["slow"], 4),
            "violating": (burns["fast"] > burn_thr
                          and burns["slow"] > burn_thr),
            "windows": [fast_w, slow_w],
        })

    max_err = _cfg_get(slo, "max_error_rate")
    if max_err is not None:
        max_err = float(max_err)
        err_metric = _cfg_get(slo, "error_metric",
                              "serve_llm_requests_total")
        err_tags = _cfg_get(slo, "error_tags") or {"finish_reason": "error"}
        tot_metric = _cfg_get(slo, "total_metric",
                              "serve_llm_requests_total")
        burns = {}
        for label, w in (("fast", fast_w), ("slow", slow_w)):
            bad = query(err_metric, window=w, agg="rate",
                        tags=dict(err_tags)).get("value") or 0.0
            total = query(tot_metric, window=w, agg="rate").get("value") \
                or 0.0
            frac = bad / total if total > 0 else 0.0
            burns[label] = frac / max_err if max_err > 0 else 0.0
        out.append({
            "objective": "error_rate", "metric": err_metric,
            "target": max_err,
            "burn_fast": round(burns["fast"], 4),
            "burn_slow": round(burns["slow"], 4),
            "violating": (burns["fast"] > burn_thr
                          and burns["slow"] > burn_thr),
            "windows": [fast_w, slow_w],
        })
    return out


def evaluate_tenant_slo(slo, query: Callable[..., Dict],
                        tenants: List[str]) -> List[Dict]:
    """Per-tenant burn rows (ROADMAP item 2d): the deployment's latency
    objective re-evaluated against each tenant's OWN observations
    (``tenant_latency_metric``, default the proxy-recorded
    ``serve_tenant_ttft_ms``, filtered by ``tags={"tenant": ...}``).
    Rows carry ``"tenant"`` and feed the same :class:`BurnRateScaler`
    input list as the aggregate rows — the scaler takes the max burn
    across rows, so ONE tenant burning its budget raises the deployment
    target even while the aggregate p95 looks healthy: tenancy shapes
    capacity, not just admission. A tenant with no samples in either
    window burns 0 and is dropped (absent ≠ violating)."""
    threshold = _cfg_get(slo, "threshold_ms")
    if threshold is None:
        threshold = _cfg_get(slo, "p95_ttft_ms")
    if threshold is None or not tenants:
        return []
    budget = float(_cfg_get(slo, "budget_fraction", 0.05) or 0.05)
    metric = _cfg_get(slo, "tenant_latency_metric",
                      "serve_tenant_ttft_ms")
    fast_w = float(_cfg_get(slo, "fast_window_s", 30.0) or 30.0)
    slow_w = float(_cfg_get(slo, "slow_window_s", 120.0) or 120.0)
    burn_thr = float(_cfg_get(slo, "burn_threshold", 1.0) or 1.0)
    out: List[Dict] = []
    for tenant in tenants:
        burns = {}
        seen = False
        for label, w in (("fast", fast_w), ("slow", slow_w)):
            r = query(metric, window=w, agg="frac_over",
                      threshold=float(threshold),
                      tags={"tenant": tenant})
            frac = r.get("value")
            seen = seen or frac is not None
            burns[label] = (frac or 0.0) / budget
        if not seen:
            continue
        out.append({
            "objective": "tenant_latency", "tenant": tenant,
            "metric": metric, "target": float(threshold),
            "budget_fraction": budget,
            "burn_fast": round(burns["fast"], 4),
            "burn_slow": round(burns["slow"], 4),
            "violating": (burns["fast"] > burn_thr
                          and burns["slow"] > burn_thr),
            "windows": [fast_w, slow_w],
        })
    return out


class BurnRateScaler:
    """Burn-driven replica-target policy — the consumer of the rows
    ``evaluate_slo`` produces (ROADMAP item 2's "control loop
    remaining"). One instance per deployment, held by the controller.

    Decisions are deliberately conservative (SRE multiwindow rule +
    hold + cooldown), because replica churn is the most expensive thing
    a TPU serving fleet can do:

    - **Upscale** only when an objective is *violating* (BOTH burn
      windows above threshold — ``evaluate_slo`` already applies the
      multiwindow rule, so an instant spike that lights up only the
      fast window never reaches here as violating) and has stayed
      violating for ``burn_upscale_hold_s``. The new target scales with
      the slow-window burn (burning 2x over budget doubles the target)
      but always moves by at least one replica.
    - **Downscale** only when every burn is below
      ``burn_release_threshold`` AND the measured load per replica is
      under half the autoscaler's ``target_ongoing_requests`` for
      ``burn_downscale_idle_s`` — idle capacity releases, a loaded but
      healthy fleet does not.
    - ``burn_cooldown_s`` separates consecutive actions in either
      direction so the loop cannot flap faster than the windows can
      re-fill with post-action samples.

    Pure: ``decide`` takes ``now`` and mutates only this object, so
    tests drive it with a fake clock and a synthetic metrics ring."""

    def __init__(self):
        self._violating_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_action_t: Optional[float] = None

    def decide(self, auto, rows: List[Dict], target: int,
               total_load: float, now: float) -> int:
        import math
        lo = int(_cfg_get(auto, "min_replicas", 1) or 1)
        hi = int(_cfg_get(auto, "max_replicas", 4) or 4)
        hold = float(_cfg_get(auto, "burn_upscale_hold_s", 6.0))
        idle_s = float(_cfg_get(auto, "burn_downscale_idle_s", 60.0))
        cooldown = float(_cfg_get(auto, "burn_cooldown_s", 30.0))
        release = float(_cfg_get(auto, "burn_release_threshold", 0.25))
        target_ongoing = float(
            _cfg_get(auto, "target_ongoing_requests", 2.0) or 2.0)
        violating = any(r.get("violating") for r in rows)
        burn_slow = max((r.get("burn_slow") or 0.0 for r in rows),
                        default=0.0)
        burn_fast = max((r.get("burn_fast") or 0.0 for r in rows),
                        default=0.0)
        in_cooldown = (self._last_action_t is not None
                       and now - self._last_action_t < cooldown)

        if violating:
            self._idle_since = None
            if self._violating_since is None:
                self._violating_since = now
            sustained = now - self._violating_since >= hold
            if sustained and not in_cooldown and target < hi:
                desired = min(hi, max(
                    target + 1,
                    math.ceil(target * min(max(burn_slow, 1.0), 2.0))))
                self._last_action_t = now
                self._violating_since = now   # re-arm the hold
                return desired
            return target

        self._violating_since = None
        idle = (burn_fast < release and burn_slow < release
                and total_load < 0.5 * target_ongoing * max(target, 1))
        if not idle:
            self._idle_since = None
            return target
        if self._idle_since is None:
            self._idle_since = now
        if (now - self._idle_since >= idle_s and not in_cooldown
                and target > lo):
            self._last_action_t = now
            self._idle_since = now            # step down one per cooldown
            return target - 1
        return target


class SloTracker:
    """Transition memory + emission. One per controller; keys are
    (app, deployment, objective)."""

    def __init__(self):
        self._violating: Dict[tuple, bool] = {}
        self._gauges = None

    def _ensure_gauges(self):
        if self._gauges is None:
            from ray_tpu.util.metrics import Gauge
            self._gauges = {
                "burn": Gauge(
                    "slo_burn_rate",
                    "error-budget burn rate per objective (1.0 = budget "
                    "consumed exactly at the allowed pace)",
                    tag_keys=("app", "deployment", "objective", "window")),
                "violating": Gauge(
                    "slo_violating",
                    "1 while both burn windows exceed the threshold",
                    tag_keys=("app", "deployment", "objective")),
                "tenant_burn": Gauge(
                    "slo_tenant_burn_rate",
                    "per-tenant error-budget burn rate (slow window)",
                    tag_keys=("app", "deployment", "tenant")),
            }
        return self._gauges

    def update(self, app: str, deployment: str, slo,
               query: Callable[..., Dict],
               tenants: Optional[List[str]] = None) -> List[Dict]:
        """Evaluate + publish. Returns the evaluation rows — aggregate
        objectives first, then per-tenant rows when ``tenants`` is
        given (surfaced via the controller's get_slo_status; the whole
        list feeds BurnRateScaler, so tenant burn shapes capacity)."""
        from ray_tpu._private import events
        rows = evaluate_slo(slo, query)
        g = self._ensure_gauges()
        if tenants:
            trows = evaluate_tenant_slo(slo, query, tenants)
            for row in trows:
                g["tenant_burn"].set(
                    row["burn_slow"],
                    tags={"app": app, "deployment": deployment,
                          "tenant": row["tenant"]})
                key = (app, deployment, "tenant:" + row["tenant"])
                was = self._violating.get(key, False)
                self._violating[key] = row["violating"]
                if row["violating"] and not was:
                    events.record_instant(
                        "slo.violation", category="serve", app=app,
                        deployment=deployment, objective="tenant_latency",
                        tenant=row["tenant"], target=row["target"],
                        burn_fast=row["burn_fast"],
                        burn_slow=row["burn_slow"])
                    logger.warning(
                        "tenant SLO violation: %s/%s tenant=%s burn "
                        "fast=%.2f slow=%.2f", app, deployment,
                        row["tenant"], row["burn_fast"], row["burn_slow"])
                elif was and not row["violating"]:
                    events.record_instant(
                        "slo.recovered", category="serve", app=app,
                        deployment=deployment, objective="tenant_latency",
                        tenant=row["tenant"],
                        burn_fast=row["burn_fast"],
                        burn_slow=row["burn_slow"])
            rows = rows + trows
        for row in rows:
            if row.get("tenant"):
                continue   # published above with tenant tags
            tags = {"app": app, "deployment": deployment,
                    "objective": row["objective"]}
            g["burn"].set(row["burn_fast"], tags={**tags, "window": "fast"})
            g["burn"].set(row["burn_slow"], tags={**tags, "window": "slow"})
            g["violating"].set(1.0 if row["violating"] else 0.0, tags=tags)
            key = (app, deployment, row["objective"])
            was = self._violating.get(key, False)
            self._violating[key] = row["violating"]
            if row["violating"] and not was:
                events.record_instant(
                    "slo.violation", category="serve", app=app,
                    deployment=deployment, objective=row["objective"],
                    metric=row["metric"], target=row["target"],
                    burn_fast=row["burn_fast"], burn_slow=row["burn_slow"])
                logger.warning(
                    "SLO violation: %s/%s %s burn fast=%.2f slow=%.2f "
                    "(target %s)", app, deployment, row["objective"],
                    row["burn_fast"], row["burn_slow"], row["target"])
            elif was and not row["violating"]:
                events.record_instant(
                    "slo.recovered", category="serve", app=app,
                    deployment=deployment, objective=row["objective"],
                    burn_fast=row["burn_fast"], burn_slow=row["burn_slow"])
        return rows
