"""Multi-host (slice-sharded) serve replicas: one replica = one worker
group spanning a TPU slice, serving a model sharded over the group's
global device mesh (SURVEY §7.2 step 10; reference replica lifecycle:
python/ray/serve/_private/deployment_state.py:1232 — the reference has no
multi-host replica, this is the TPU-native extension of it).

Shape: a replica group is `num_hosts` ReplicaShard actors gang-placed by
a placement group (STRICT_SPREAD across the hosts of one slice when a
topology is given, PACK otherwise), joined into one jax.distributed world
through the GCS-KV coordinator rendezvous (the NCCL/TCP-store
replacement). Every rank constructs the user callable — its __init__
builds the model sharded over the *global* mesh — and rank 0 is the
ingress: routers hold only the rank-0 handle, which fans each request out
to the peer ranks so every process enters the same SPMD computation, and
returns its own (rank-0) result.

SPMD discipline: multi-host XLA programs deadlock if two requests
interleave across ranks in different orders, so the rank-0 facade admits
one request into the compute at a time (queue depth still reported for
autoscaling). Batching therefore belongs *inside* the callable
(@serve.batch) where it rides one SPMD entry.

Failure semantics match training slices: one dead rank invalidates the
whole group (ICI collectives span every host), so health checks probe all
ranks and the controller replaces the entire group, never a single rank.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


class ReplicaShard:
    """One rank of a sharded replica group (actor; max_concurrency must
    leave room for health/queue probes while a request runs)."""

    def __init__(self, rank: int, world_size: int):
        self._rank = rank
        self._world = world_size
        self._callable = None
        self._is_function = False
        self._peers: List = []
        self._ongoing = 0
        self._lock = threading.Lock()
        # serializes SPMD entry on rank 0 (see module docstring)
        self._spmd_lock = threading.Lock()
        # set when a stream died mid-collective: the gang's ranks are
        # desynchronized and must be replaced as a unit
        self._wedged = False
        self._draining = False

    def setup_distributed(self, group_name: str) -> bool:
        """Join the group's jax.distributed world (KV rendezvous). Must
        run before any jax use in this process."""
        from ray_tpu.util.collective import _init_jax_distributed
        _init_jax_distributed(self._world, self._rank, group_name)
        return True

    def init_callable(self, serialized_callable: bytes, init_args: Tuple,
                      init_kwargs: Dict, is_function: bool) -> bool:
        """Construct the user callable on THIS rank. All ranks run the
        same __init__, so a model sharded with jax.device_put /
        make_array_from_process_local_data lands distributed across the
        group."""
        import cloudpickle
        target = cloudpickle.loads(serialized_callable)
        self._is_function = is_function
        if is_function:
            self._callable = target
        else:
            self._callable = target(*init_args, **init_kwargs)
        return True

    def set_peers(self, peers: List) -> bool:
        """Rank 0 only: handles to ranks 1..world-1, fan-out targets."""
        self._peers = list(peers)
        return True

    # ------------------------------------------------------------ data plane
    def handle_request(self, method: str, args: Tuple, kwargs: Dict):
        """Rank-0 ingress: admit one SPMD request, fan out to peers, run
        the local shard, surface the first failure (peer errors included
        — a hung peer would otherwise deadlock the *next* request)."""
        import ray_tpu
        with self._lock:
            self._ongoing += 1
        try:
            with self._spmd_lock:
                refs = [p.run_shard.remote(method, args, kwargs)
                        for p in self._peers]
                try:
                    result = self.run_shard(method, args, kwargs)
                finally:
                    # peers must finish their shard of this request before
                    # the next one may enter (SPMD ordering)
                    ray_tpu.get(refs, timeout=300)
            return result
        finally:
            with self._lock:
                self._ongoing -= 1

    def run_shard(self, method: str, args: Tuple, kwargs: Dict):
        """Execute the user method on this rank's shard of the world."""
        kwargs = dict(kwargs)
        kwargs.pop("__serve_model_id", None)
        if self._is_function:
            fn = self._callable
        else:
            fn = getattr(self._callable, method)
        import asyncio
        import inspect
        if inspect.iscoroutinefunction(fn):
            from ray_tpu._private.worker import global_worker
            return asyncio.run_coroutine_threadsafe(
                fn(*args, **kwargs), global_worker.core.loop).result()
        return fn(*args, **kwargs)

    # --------------------------------------------------------- streaming
    def handle_stream(self, method: str, args: Tuple, kwargs: Dict):
        """Rank-0 streaming ingress (token streaming): every rank runs
        the same generator method; rank 0 yields its chunks to the
        router while peers drain theirs. Lockstep comes from the SPMD
        collectives themselves — with one stream admitted at a time
        (the SPMD lock), each rank's generator steps through the same
        collective sequence and the rendezvous throttles whoever runs
        ahead.

        Abandoned streams: if the client walks away mid-collective, rank
        0's generator closes but the peers stay parked at the
        rendezvous. The drain wait is BOUNDED; on timeout the group
        marks itself wedged — health checks then fail and the
        controller replaces the whole gang (a half-finished SPMD world
        cannot be safely reused)."""
        import ray_tpu
        kwargs = dict(kwargs)
        kwargs.pop("__serve_model_id", None)
        with self._lock:
            self._ongoing += 1
        try:
            with self._spmd_lock:
                refs = [p.run_shard_drain.remote(method, args, kwargs)
                        for p in self._peers]
                completed = False
                try:
                    fn = self._callable if self._is_function \
                        else getattr(self._callable, method)
                    for chunk in fn(*args, **kwargs):
                        yield chunk
                    completed = True
                finally:
                    try:
                        ray_tpu.get(refs,
                                    timeout=300 if completed else 15)
                    except Exception:
                        self._wedged = True
                        raise
                if completed:
                    self._verify_stream_digest()
        finally:
            with self._lock:
                self._ongoing -= 1

    def _verify_stream_digest(self):
        """Digest agreement on sampled tokens (opt-in: the callable
        exposes ``last_stream_digest``). After a completed stream every
        rank must have produced the same token bytes — a mismatch means
        the SPMD invariant broke (rank-local rng drift, bad kernel) and
        the gang is serving split-brain output, so it wedges itself for
        whole-group replacement rather than continue."""
        import ray_tpu
        fn = getattr(self._callable, "last_stream_digest", None)
        if fn is None or not self._peers:
            return
        local = fn()
        theirs = ray_tpu.get(
            [p.run_shard.remote("last_stream_digest", (), {})
             for p in self._peers], timeout=30)
        for rank, d in enumerate(theirs, start=1):
            if d != local:
                self._wedged = True
                raise RuntimeError(
                    f"sharded replica digest divergence: rank 0 "
                    f"produced {local}, rank {rank} produced {d} — "
                    f"gang wedged for replacement")

    def run_shard_drain(self, method: str, args: Tuple, kwargs: Dict):
        """Peer side of a streamed request: step the generator to
        exhaustion (outputs discarded — rank 0 owns the response)."""
        kwargs = dict(kwargs)
        kwargs.pop("__serve_model_id", None)
        fn = self._callable if self._is_function \
            else getattr(self._callable, method)
        n = 0
        for _ in fn(*args, **kwargs):
            n += 1
        return n

    # --------------------------------------------------------- control plane
    def get_queue_len(self) -> int:
        return self._ongoing

    def begin_drain(self) -> bool:
        """Drain notice for the whole gang (ingress is rank 0, so
        flipping the rank-0 callable stops new admissions)."""
        self._draining = True
        fn = getattr(self._callable, "begin_drain", None)
        if fn is not None:
            try:
                fn()
            except Exception:
                logger.warning("callable begin_drain failed",
                               exc_info=True)
        return True

    def get_runtime_state(self) -> Dict:
        return {"queue_len": self._ongoing,
                "draining": getattr(self, "_draining", False)}

    def check_health(self) -> bool:
        """Rank 0 probes every peer: one dead rank = unhealthy group, so
        the controller replaces the gang as a unit (slice semantics)."""
        import ray_tpu
        if self._wedged:
            raise ray_tpu.ActorDiedError(
                "sharded replica gang wedged by an abandoned stream")
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()
        if self._peers:
            ray_tpu.get([p.check_peer_health.remote() for p in self._peers],
                        timeout=25)
        return True

    def check_peer_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    def reconfigure(self, user_config) -> bool:
        import ray_tpu
        refs = [p.reconfigure_shard.remote(user_config)
                for p in self._peers]
        self.reconfigure_shard(user_config)
        ray_tpu.get(refs, timeout=60)
        return True

    def reconfigure_shard(self, user_config) -> bool:
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True


def create_sharded_group(spec: Dict) -> Tuple[object, Dict]:
    """Gang-create one sharded replica group for `spec` (controller
    helper). Returns (rank0_handle, group_record) where group_record =
    {"members": [handles], "pg": placement_group} — the controller keeps
    it so kill/drain retires the whole gang and releases the bundle.

    Placement: with config["topology"] (e.g. "v4-32") the bundles come
    from train/slice.py — pinned to ONE healthy slice, STRICT_SPREAD over
    its hosts. Without a topology, `num_hosts` plain bundles placed PACK
    (multi-process on commodity nodes — the CPU CI shape)."""
    import uuid

    import ray_tpu
    from ray_tpu.util import (PlacementGroupSchedulingStrategy,
                              placement_group, remove_placement_group)

    cfg = spec["config"]
    n = int(cfg.get("num_hosts") or 1)
    topology = cfg.get("topology")
    opts = dict(cfg.get("ray_actor_options") or {})
    res = {"CPU": opts.get("num_cpus", 0.25)}
    if opts.get("num_tpus"):
        res["TPU"] = opts["num_tpus"]
    for k, v in (opts.get("resources") or {}).items():
        res[k] = v
    strategy = "PACK"
    bundles = [dict(res) for _ in range(n)]
    if topology:
        from ray_tpu.train import slice as slice_lib
        n_hosts, chips = slice_lib.slice_shape(topology)
        if n_hosts != n:
            raise ValueError(f"topology {topology} has {n_hosts} hosts; "
                             f"num_hosts={n} must match")
        pod = slice_lib.pick_slice(ray_tpu.nodes(), topology)
        if pod is None:
            raise RuntimeError(f"no healthy {topology} slice available")
        bundles = slice_lib.slice_bundles(pod, topology, res)
        strategy = "STRICT_SPREAD"
    pg = placement_group(bundles, strategy=strategy)
    if not pg.wait(timeout=120):
        remove_placement_group(pg)
        raise RuntimeError(
            f"placement group for sharded replica ({n} hosts) "
            f"not schedulable: {bundles}")
    max_ongoing = cfg.get("max_ongoing_requests", 16)
    actor_cls = ray_tpu.remote(ReplicaShard)
    members = []
    try:
        for rank in range(n):
            a_opts = dict(
                max_concurrency=max_ongoing + 4,
                resources=dict(bundles[rank]),
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    pg, placement_group_bundle_index=rank))
            if opts.get("runtime_env"):
                a_opts["runtime_env"] = opts["runtime_env"]
            members.append(actor_cls.options(**a_opts).remote(rank, n))
        group_name = f"serve-shard-{uuid.uuid4().hex[:8]}"
        ray_tpu.get([m.setup_distributed.remote(group_name)
                     for m in members], timeout=300)
        ray_tpu.get([m.init_callable.remote(
            spec["callable"], tuple(spec["init_args"]),
            spec["init_kwargs"], spec["is_function"])
            for m in members], timeout=600)
        ray_tpu.get(members[0].set_peers.remote(members[1:]), timeout=60)
    except Exception:
        for m in members:
            try:
                ray_tpu.kill(m)
            except Exception:
                pass
        try:
            remove_placement_group(pg)
        except Exception:
            pass
        raise
    return members[0], {"members": members, "pg": pg}


def kill_group(group: Dict) -> None:
    """Tear down every rank + release the gang's placement group."""
    import ray_tpu
    from ray_tpu.util import remove_placement_group
    for m in group.get("members", []):
        try:
            ray_tpu.kill(m)
        except Exception:
            pass
    pg = group.get("pg")
    if pg is not None:
        try:
            remove_placement_group(pg)
        except Exception:
            pass
