"""Cluster weight-source resolution for replica construction.

ROADMAP item 3 leftover: a fleet shell revival used to RE-RUN the
deployment's ``params_fn`` — a full checkpoint read (or re-init) inside
every cold start, on every node, every time a scaled-to-zero deployment
woke up. The weight-distribution plane (PR 11) already solves exactly
this: one loaded tree broadcast once lands in every node's pinned arena,
and every later attach is a zero-copy local get.

``resolve_weight_source(key, loader)`` is the default path LLMDeployment
routes ``params_fn`` through (``fleet_weights_from_arena`` flag):

1. the GCS KV (namespace ``serve_weights``) is probed for a recorded
   broadcast ref under ``key`` — hit → ``ray_tpu.get`` attaches the tree
   from the local arena (cross-node pulls ride the zero-copy data
   plane); a stale/lost ref falls through;
2. miss → ``loader()`` runs ONCE (the only attach that pays the load),
   the host tree is published via ``ray_tpu.broadcast_weights`` — or a
   plain ``ray_tpu.put`` when the weight plane is unavailable (single
   node, no data plane) — and the ref is recorded for every future
   attach, shell revivals included.

``checkpoint_weight_source(path)`` builds a params_fn whose miss path is
``sharded_checkpoint.restore_and_broadcast`` — one host reads storage,
the fleet attaches from local arenas.

Outside a cluster everything degrades to a bare ``loader()`` call, so
the same deployment code runs in unit tests and bare scripts.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)

KV_NS = "serve_weights"


def _worker():
    from ray_tpu import _get_worker
    return _get_worker()


def _connected() -> bool:
    try:
        import ray_tpu
        return ray_tpu.is_initialized()
    except Exception:
        return False


def _host_tree(params: Any) -> Any:
    """Pull a params tree to host (numpy) leaves — the broadcastable
    form; device placement happens per-attach anyway."""
    import jax
    import numpy as np
    return jax.tree.map(lambda a: np.asarray(a), params)


def cached_ref(key: str):
    """The recorded broadcast ref for ``key``, or None."""
    import cloudpickle
    try:
        blob = _worker().gcs_call("kv_get", ns=KV_NS, key=key.encode())
    except Exception:
        return None
    if not blob:
        return None
    try:
        return cloudpickle.loads(blob)
    except Exception:
        return None


def record_ref(key: str, ref) -> None:
    import cloudpickle
    _worker().gcs_call("kv_put", ns=KV_NS, key=key.encode(),
                       value=cloudpickle.dumps(ref))


def clear_ref(key: str) -> None:
    try:
        _worker().gcs_call("kv_del", ns=KV_NS, key=key.encode())
    except Exception:
        logger.debug("weight-source kv_del failed for %s", key,
                     exc_info=True)


def publish_weights(key: str, params: Any):
    """Broadcast a loaded tree cluster-wide (plain-put fallback when the
    weight plane is unavailable) and record the ref under ``key``.
    Returns the ref, or None when even the put failed — callers always
    still hold the in-memory tree, so publish failures only cost the
    NEXT attach a reload."""
    import ray_tpu
    host = _host_tree(params)
    try:
        ref = ray_tpu.broadcast_weights(host)
        via = "broadcast"
    except Exception:
        try:
            ref = ray_tpu.put(host)
            via = "put"
        except Exception:
            logger.warning("weight publish failed for %s", key,
                           exc_info=True)
            return None
    try:
        record_ref(key, ref)
    except Exception:
        logger.warning("weight-source ref record failed for %s", key,
                       exc_info=True)
        return None
    from ray_tpu._private import events
    events.record_instant("serve.weight_publish", category="serve",
                          key=key, via=via)
    return ref


def resolve_weight_source(key: Optional[str], loader: Callable[[], Any],
                          *, enabled: Optional[bool] = None,
                          timeout_s: Optional[float] = None) -> Any:
    """Resolve a deployment's params through the cluster weight plane
    (see module docstring). Any failure along the arena path falls back
    to ``loader()`` — serving never breaks on weight-plane trouble."""
    from ray_tpu._private.config import cfg
    if enabled is None:
        enabled = cfg.fleet_weights_from_arena
    if not enabled or not key or not _connected():
        return loader()
    from ray_tpu._private import events
    ref = cached_ref(key)
    if ref is not None:
        try:
            import ray_tpu
            params = ray_tpu.get(
                ref, timeout=(timeout_s if timeout_s is not None
                              else cfg.fleet_attach_timeout_s))
            events.record_instant("serve.weight_attach", category="serve",
                                  key=key, source="arena")
            return params
        except Exception:
            # ref outlived its object (node loss, store restart):
            # forget it and reload below
            logger.info("weight-source ref for %s unreadable; reloading",
                        key, exc_info=True)
            clear_ref(key)
    params = loader()
    published = publish_weights(key, params) is not None
    events.record_instant("serve.weight_attach", category="serve",
                          key=key, source="loader", published=published)
    return params


def checkpoint_weight_source(path: str,
                             key: Optional[str] = None
                             ) -> Callable[[], Any]:
    """A ``params_fn`` whose cold path is
    ``sharded_checkpoint.restore_and_broadcast``: the first attach reads
    the checkpoint off storage ONCE and fans it out over the weight
    plane; every other attach (and every shell revival) gets a local
    arena attach. Outside a cluster it reads the checkpoint directly."""
    key = key or f"ckpt/{path}"

    def params_fn():
        from ray_tpu.train.sharded_checkpoint import restore_host_arrays
        if not _connected():
            return restore_host_arrays(path)

        def loader():
            return restore_host_arrays(path)
        return resolve_weight_source(key, loader)
    return params_fn
