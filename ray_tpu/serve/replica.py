"""Replica actor: hosts one copy of a deployment's callable (reference:
python/ray/serve/_private/replica.py:233 ReplicaActor + UserCallableWrapper
:810). Runs with max_concurrency = max_ongoing_requests so requests overlap
and health probes are never stuck behind user code; tracks its ongoing
count for autoscaling."""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Tuple

logger = logging.getLogger(__name__)


class ReplicaDrainingError(Exception):
    """Raised at the replica boundary for calls that arrive AFTER a
    drain notice (router staleness window): the replica is alive and
    finishing in-flight work but takes nothing new. The handle layer
    treats it like replica death — refresh the routing table and
    re-route once — so clients of a preempted replica see a survivor,
    not an error."""


class Replica:
    def __init__(self, serialized_callable: bytes, init_args: Tuple,
                 init_kwargs: Dict, is_function: bool):
        self._init_state()
        self._init_callable(serialized_callable, init_args, init_kwargs,
                            is_function)

    # split so a pre-warmed ReplicaShell (serve/fleet.py) can pay the
    # process/import cost at pool time and run the callable
    # construction later, at attach
    def _init_state(self):
        self._callable = None
        self._is_function = False
        self._ongoing = 0
        self._lock = threading.Lock()
        self._draining = False

    def _init_callable(self, serialized_callable: bytes, init_args: Tuple,
                       init_kwargs: Dict, is_function: bool):
        import cloudpickle
        target = cloudpickle.loads(serialized_callable)
        self._is_function = is_function
        if is_function:
            self._callable = target
        else:
            self._callable = target(*init_args, **init_kwargs)
        # spot preemption notices: on GCE (or under chaos injection) a
        # watcher polls the metadata channel and flips this replica into
        # draining before the platform kills the VM — the controller
        # sees it on its next state probe and pre-starts a replacement
        from ray_tpu._private.accelerators import tpu as tpu_accel
        if tpu_accel.preemption_watch_enabled():
            threading.Thread(target=self._preemption_watch,
                             name="serve-preempt-watch",
                             daemon=True).start()

    def _preemption_watch(self):
        from ray_tpu._private.accelerators import tpu as tpu_accel
        poll_s = float(os.environ.get("RAY_TPU_PREEMPT_POLL_S", "1.0"))
        while not self._draining:
            try:
                if tpu_accel.check_preemption_notice():
                    logger.warning("preemption notice received; draining")
                    self.begin_drain()
                    return
            except Exception:
                logger.debug("preemption poll failed", exc_info=True)
            time.sleep(poll_s)

    # ------------------------------------------------------------- draining
    def begin_drain(self) -> bool:
        """Preemption notice / graceful retirement: stop taking new
        work. The routing layer drops this replica on the controller's
        next probe; streams already in flight run to completion (the
        engine's drain mode refuses only NEW submissions). Idempotent."""
        with self._lock:
            if self._draining:
                return True
            self._draining = True
        fn = getattr(self._callable, "begin_drain", None)
        if fn is not None:
            try:
                fn()
            except Exception:
                logger.warning("callable begin_drain failed",
                               exc_info=True)
        return True

    def get_runtime_state(self) -> Dict:
        """One-probe view for the controller's reconcile tick: queue
        depth (autoscaling + router load push) and the draining flag
        (preemption pickup)."""
        return {"queue_len": self._ongoing, "draining": self._draining}

    @staticmethod
    def _stash_peer_hint(kwargs: Dict):
        """Routing metadata from the handle's prefix router: which OTHER
        replica covers this prompt deepest. Parked in a thread-local for
        the decode tier's KV-fabric rung (serve/disagg.py) — advisory,
        so any failure here just costs the optimization."""
        hint = kwargs.pop("__serve_peer_hint", None)
        if hint is not None:
            try:
                from ray_tpu.serve.disagg import set_peer_hint
                set_peer_hint(hint)
            except Exception:
                pass

    def handle_request(self, method: str, args: Tuple, kwargs: Dict):
        import ray_tpu
        from ray_tpu import ObjectRef
        if self._draining:
            raise ReplicaDrainingError(
                "replica is draining (preemption notice); re-route")
        # composed calls pass upstream DeploymentResponses as refs; resolve
        # to values before invoking user code (reference: handle.py resolves
        # nested DeploymentResponses)
        args = tuple(ray_tpu.get(a) if isinstance(a, ObjectRef) else a
                     for a in args)
        kwargs = {k: (ray_tpu.get(v) if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
        model_id = kwargs.pop("__serve_model_id", "")
        kwargs.pop("__serve_tenant", "")   # routing metadata, not an arg
        Replica._stash_peer_hint(kwargs)
        from ray_tpu._private import events
        with self._lock:
            self._ongoing += 1
        try:
            if self._is_function:
                fn = self._callable
            else:
                fn = getattr(self._callable, method)
            import asyncio
            import inspect

            from ray_tpu.serve import multiplex
            # replica phase span: parents under this actor task's
            # propagated trace context (set by the executing worker), so
            # user-code time separates from arg-resolution time above
            rspan = events.start_span("replica.call", category="serve",
                                      method=method, ongoing=self._ongoing)
            if inspect.iscoroutinefunction(fn):
                # we're on an executor thread; hop onto the worker loop —
                # the model-id contextvar is set inside the coroutine so
                # it lives in the loop-side execution context
                async def _call():
                    tok = multiplex._set_model_id(model_id)
                    try:
                        return await fn(*args, **kwargs)
                    finally:
                        multiplex._current_model_id.reset(tok)
                from ray_tpu._private.worker import global_worker
                try:
                    return asyncio.run_coroutine_threadsafe(
                        _call(), global_worker.core.loop).result()
                finally:
                    rspan.end()
            tok = multiplex._set_model_id(model_id)
            try:
                return fn(*args, **kwargs)
            finally:
                rspan.end()
                multiplex._current_model_id.reset(tok)
        finally:
            with self._lock:
                self._ongoing -= 1

    # ------------------------------------------------------------ streaming
    def handle_stream(self, method: str, args: Tuple, kwargs: Dict):
        """Generator method invoked with num_returns='streaming': each
        yielded chunk becomes one item on the caller's
        ObjectRefGenerator, riding the core streaming-generator protocol
        (round-5; replaces the round-4 bespoke start_stream/stream_next
        polling. Reference: streaming DeploymentResponseGenerator over
        ObjectRefGenerator, serve/handle.py)."""
        from ray_tpu._private import events
        from ray_tpu.serve import multiplex
        if self._draining:
            raise ReplicaDrainingError(
                "replica is draining (preemption notice); re-route")
        model_id = kwargs.pop("__serve_model_id", "")
        kwargs.pop("__serve_tenant", "")
        Replica._stash_peer_hint(kwargs)
        with self._lock:
            self._ongoing += 1
        # the body's first resumption runs under the streaming task's
        # trace context, so this span parents under the replica task —
        # ended in the outer finally (which also runs on close())
        sspan = events.start_span("replica.stream", category="serve",
                                  method=method)
        chunks = 0      # wire frames yielded (coalesced batches count 1)
        items = 0       # items inside them (tokens, for coalesced LLMs)
        try:
            fn = self._callable if self._is_function \
                else getattr(self._callable, method)
            # the streaming executor resumes each next() on whatever
            # pool thread is free: set/reset the multiplex contextvar
            # WITHIN each resumption (a token created on one thread
            # cannot be reset on another, and a cross-thread reset in a
            # finally would leak the _ongoing decrement below)
            tok = multiplex._set_model_id(model_id)
            try:
                it = iter(fn(*args, **kwargs))
            finally:
                multiplex._current_model_id.reset(tok)
            try:
                while True:
                    tok = multiplex._set_model_id(model_id)
                    try:
                        chunk = next(it)
                    except StopIteration:
                        break
                    finally:
                        multiplex._current_model_id.reset(tok)
                    chunks += 1
                    items += (len(chunk)
                              if isinstance(chunk, (list, tuple)) else 1)
                    yield chunk
            finally:
                # consumer walked away (GeneratorExit lands on the yield
                # above) or the stream errored: close the USER generator
                # deterministically so its finally/except runs NOW —
                # engine slots, file handles etc. free immediately
                # instead of at some future GC pass
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass
        finally:
            sspan.end(chunks=chunks, items=items)
            with self._lock:
                self._ongoing -= 1

    def get_queue_len(self) -> int:
        return self._ongoing

    def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    def reconfigure(self, user_config):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True
