"""Replica actor: hosts one copy of a deployment's callable (reference:
python/ray/serve/_private/replica.py:233 ReplicaActor + UserCallableWrapper
:810). Runs with max_concurrency = max_ongoing_requests so requests overlap
and health probes are never stuck behind user code; tracks its ongoing
count for autoscaling."""

from __future__ import annotations

import threading
from typing import Any, Dict, Tuple


class Replica:
    def __init__(self, serialized_callable: bytes, init_args: Tuple,
                 init_kwargs: Dict, is_function: bool):
        import cloudpickle
        target = cloudpickle.loads(serialized_callable)
        self._is_function = is_function
        if is_function:
            self._callable = target
        else:
            self._callable = target(*init_args, **init_kwargs)
        self._ongoing = 0
        self._lock = threading.Lock()

    def handle_request(self, method: str, args: Tuple, kwargs: Dict):
        import ray_tpu
        from ray_tpu import ObjectRef
        # composed calls pass upstream DeploymentResponses as refs; resolve
        # to values before invoking user code (reference: handle.py resolves
        # nested DeploymentResponses)
        args = tuple(ray_tpu.get(a) if isinstance(a, ObjectRef) else a
                     for a in args)
        kwargs = {k: (ray_tpu.get(v) if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
        model_id = kwargs.pop("__serve_model_id", "")
        with self._lock:
            self._ongoing += 1
        try:
            if self._is_function:
                fn = self._callable
            else:
                fn = getattr(self._callable, method)
            import asyncio
            import inspect

            from ray_tpu.serve import multiplex
            if inspect.iscoroutinefunction(fn):
                # we're on an executor thread; hop onto the worker loop —
                # the model-id contextvar is set inside the coroutine so
                # it lives in the loop-side execution context
                async def _call():
                    tok = multiplex._set_model_id(model_id)
                    try:
                        return await fn(*args, **kwargs)
                    finally:
                        multiplex._current_model_id.reset(tok)
                from ray_tpu._private.worker import global_worker
                return asyncio.run_coroutine_threadsafe(
                    _call(), global_worker.core.loop).result()
            tok = multiplex._set_model_id(model_id)
            try:
                return fn(*args, **kwargs)
            finally:
                multiplex._current_model_id.reset(tok)
        finally:
            with self._lock:
                self._ongoing -= 1

    # ------------------------------------------------------------ streaming
    def start_stream(self, method: str, args: Tuple, kwargs: Dict) -> str:
        """Run a generator method; chunks buffer server-side and drain via
        stream_next (reference: streaming DeploymentResponseGenerator,
        serve/handle.py — there gRPC streaming, here chunked polls)."""
        import queue
        import threading
        import uuid
        model_id = kwargs.pop("__serve_model_id", "")
        sid = uuid.uuid4().hex
        q: "queue.Queue" = queue.Queue()
        if not hasattr(self, "_streams"):
            self._streams = {}
        self._streams[sid] = q

        def run():
            from ray_tpu.serve import multiplex
            tok = multiplex._set_model_id(model_id)
            try:
                fn = self._callable if self._is_function \
                    else getattr(self._callable, method)
                out = fn(*args, **kwargs)
                for chunk in out:
                    q.put(("chunk", chunk))
                q.put(("done", None))
            except BaseException as e:
                q.put(("error", f"{type(e).__name__}: {e}"))
            finally:
                multiplex._current_model_id.reset(tok)

        threading.Thread(target=run, daemon=True).start()
        return sid

    def stream_next(self, stream_id: str, max_n: int = 64,
                    timeout: float = 10.0):
        """Returns (chunks, done, error)."""
        import queue
        q = self._streams.get(stream_id)
        if q is None:
            return [], True, "unknown stream"
        chunks = []
        done = False
        error = None
        try:
            kind, payload = q.get(timeout=timeout)
            while True:
                if kind == "chunk":
                    chunks.append(payload)
                elif kind == "done":
                    done = True
                else:
                    error = payload
                    done = True
                if done or len(chunks) >= max_n:
                    break
                kind, payload = q.get_nowait()
        except queue.Empty:
            pass
        if done:
            self._streams.pop(stream_id, None)
        return chunks, done, error

    def get_queue_len(self) -> int:
        return self._ongoing

    def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    def reconfigure(self, user_config):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True
