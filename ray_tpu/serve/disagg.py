"""Disaggregated prefill/decode serving plane (ROADMAP item 1b/1c).

Prefill is compute-bound, decode is memory-bound; colocating them on one
replica wastes both sides of the roofline (the Gemma-on-TPU serving
study quantifies the imbalance). This module splits ``LLMDeployment``
into two tiers and turns N replica prefix caches into one logical
cluster cache:

- **Prefill tier** (:class:`PrefillLLMDeployment`): replicas run chunked
  prefill only. ``prefill_export(tokens)`` makes sure the prompt's
  chunk-aligned prefix is in the local radix cache (PR 10 blocks are
  already immutable chunk-aligned spans), copies the blocks out of the
  pool with the engine's fixed-shape export program, frames them into
  one contiguous payload, and parks it in the **pinned shared-memory
  arena** via ``ray_tpu.put`` — returning the ObjectRef, never the
  bytes. The payload therefore moves between nodes over the PR 5
  zero-copy data plane: the decode node's ``recv_into`` writes straight
  into its arena, and the import path reads ``np.frombuffer`` views of
  that region (no host staging copy; the single host->device copy is
  the irreducible one).

- **Decode tier** (:class:`DisaggLLMDeployment`): on a request whose
  prefix is not cached locally, the replica hold-submits the request
  (the scheduler keeps its FIFO position but won't admit it — the
  remote-prefill admission state), asks the prefill tier for the KV
  blocks, imports them into its own block pool + trie, and releases the
  hold. Admission then takes the ordinary radix-hit path: ``load_span``
  restores the imported blocks into scratch and only the final chunk
  prefills. Greedy output is bit-identical to the colocated path and
  ``decode_compile_count`` stays at 1 (export/import are two more
  fixed-shape programs, compiled once).

- **Cluster-wide prefix routing**: every decode replica periodically
  publishes a compact trie summary — the top-K most-recently-touched
  path fingerprints (~8 bytes per cached chunk) — to the GCS
  ``prefix_summaries`` table. The router (serve/handle.py) computes the
  incoming prompt's own chunk fingerprints and routes to the replica
  with the DEEPEST cluster-wide match; session hash breaks ties and
  handles the no-match case. N private caches become one logical cache:
  a prefix warmed on replica A serves sessions that have never touched
  A.

- **Decode→decode KV fabric** (ROADMAP item 2b): any decode replica
  whose published summary covers the prompt can serve the pinned-arena
  payload DIRECTLY to a peer via :meth:`DisaggLLMDeployment.peer_export`
  — same wire framing, same data plane, no prefill-tier funnel. The
  exporter proves the requested fingerprint against its LIVE trie
  (``RadixPrefixCache.covered_fp``) before shipping, so a stale summary
  (blocks evicted since the last publish cadence) is refused instead of
  installing KV for the wrong tokens. K concurrent exports of one hot
  fingerprint coalesce in :class:`_ExportSingleFlight` — one
  ``export_kv_blocks`` run — and when the waiters span enough distinct
  nodes the payload relays through the PR 11 broadcast tree
  (``ray_tpu.broadcast_weights``, binomial fan-out) instead of K
  point-to-point pulls (item 2c).

Fallback ladder (every rung preserves exactly-once token delivery —
nothing has streamed yet when a rung fails):

  1. cluster longest-prefix route  (router; stale summary -> rung 2)
  2. local radix hit               (no hand-off needed)
  3. decode→decode peer hand-off   (KV fabric; dead peer / stale
                                    fingerprint / empty export -> 4)
  4. KV hand-off from the prefill tier (replica death / timeout -> 5)
  5. local chunked prefill         (the PR 3 path, always available)
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

import msgpack
import numpy as np

from ray_tpu._private import events, rpc
from ray_tpu._private.config import cfg
from ray_tpu.inference.api import LLMDeployment

logger = logging.getLogger(__name__)


# ------------------------------------------------------------ KV framing
def pack_kv_spans(spans: List[Tuple[np.ndarray, ...]]) -> bytes:
    """Frame exported KV spans into one contiguous payload:
    ``[u32 header_len][msgpack {n, shape, dtype}][k0][v0][k1][v1]...``
    with raw array bytes back to back — the shape ``unpack_kv_spans``
    reads as zero-copy ``np.frombuffer`` views of the arena buffer the
    data plane received into.

    A ``kv_quant="int8"`` exporter hands 4-tuple spans ``(qk, qv,
    k_scales, v_scales)``; the header then carries ``quant: "int8"``
    plus the scale shape/dtype and each span frames as
    ``[qk][qv][ks][vs]`` — the wire payload shrinks by
    ``~itemsize * D / (D + 4)`` vs the fp framing (kv_quant.slot_gain),
    which is the disagg hand-off half of the int8 win."""
    if not spans:
        hdr = msgpack.packb({"n": 0, "shape": [], "dtype": ""})
        return len(hdr).to_bytes(4, "little") + hdr
    k0 = spans[0][0]
    meta = {"n": len(spans), "shape": list(k0.shape),
            "dtype": str(k0.dtype)}
    if len(spans[0]) == 4:
        s0 = spans[0][2]
        meta["quant"] = "int8"
        meta["sshape"] = list(s0.shape)
        meta["sdtype"] = str(s0.dtype)
    hdr = msgpack.packb(meta)
    parts = [len(hdr).to_bytes(4, "little"), hdr]
    for span in spans:
        for a in span:
            parts.append(np.ascontiguousarray(a).tobytes())
    return b"".join(parts)


def unpack_kv_spans(buf) -> List[Tuple[np.ndarray, ...]]:
    """Inverse of :func:`pack_kv_spans`. Accepts bytes or a memoryview
    (e.g. the zero-copy arena view ``ray_tpu.get`` returns) and hands
    back ``np.frombuffer`` views into it — no copy until the engine's
    one host->device put. Quantized payloads come back as the same
    4-tuples the exporter produced; ``import_kv_blocks`` accepts either
    form on either engine (host re/de-quantization bridges mixed-mode
    tiers)."""
    mv = memoryview(buf)
    hlen = int.from_bytes(mv[:4], "little")
    meta = msgpack.unpackb(bytes(mv[4:4 + hlen]), raw=False)
    n = int(meta["n"])
    if n == 0:
        return []
    shape = tuple(int(s) for s in meta["shape"])
    dtype = np.dtype(meta["dtype"])
    span_bytes = dtype.itemsize * int(np.prod(shape))
    off = 4 + hlen

    def take(nbytes, dt, shp):
        nonlocal off
        a = np.frombuffer(mv[off:off + nbytes], dt).reshape(shp)
        off += nbytes
        return a

    spans = []
    if meta.get("quant") == "int8":
        sshape = tuple(int(s) for s in meta["sshape"])
        sdtype = np.dtype(meta["sdtype"])
        sbytes = sdtype.itemsize * int(np.prod(sshape))
        for _ in range(n):
            spans.append((take(span_bytes, dtype, shape),
                          take(span_bytes, dtype, shape),
                          take(sbytes, sdtype, sshape),
                          take(sbytes, sdtype, sshape)))
        return spans
    for _ in range(n):
        spans.append((take(span_bytes, dtype, shape),
                      take(span_bytes, dtype, shape)))
    return spans


# --------------------------------------------------- summary publication
class PrefixSummaryPublisher:
    """Background publisher of one replica's trie summary into the GCS
    ``prefix_summaries`` table (cadence ``cfg.prefix_summary_interval_s``;
    rows expire after ``cfg.prefix_summary_ttl_s`` so a dead replica
    falls out of routing within one TTL). No-op outside a cluster
    (direct instantiation in tests) — start() simply doesn't spawn the
    thread when there is no runtime context to publish under."""

    def __init__(self, engine, deployment: str):
        self._engine = engine
        self._deployment = deployment
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.published = 0

    def start(self) -> "PrefixSummaryPublisher":
        if self._engine.prefix_cache is None:
            return self
        try:
            import ray_tpu
            rid = ray_tpu.get_runtime_context().get("actor_id")
        except Exception:
            return self
        if not rid:
            return self
        self._rid = rid
        self._thread = threading.Thread(
            target=self._loop, name="prefix-summary-pub", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        import ray_tpu
        while not self._stop.wait(cfg.prefix_summary_interval_s):
            cache = self._engine.prefix_cache
            if cache is None or self._engine._stop:
                return   # engine retired: let the GCS row TTL out
            try:
                s = cache.summary(cfg.prefix_summary_top_k)
                ray_tpu._get_worker().gcs_call(
                    "publish_prefix_summary", replica_id=self._rid,
                    fps=s["fps"], chunk=s["chunk"], blocks=s["blocks"],
                    deployment=self._deployment)
                self.published += 1
            except Exception:
                # routing falls back to session hash while the GCS is
                # unreachable; the next tick retries
                logger.debug("prefix summary publish failed",
                             exc_info=True)

    def stop(self):
        self._stop.set()


# ------------------------------------------------------ peer-hint channel
# The router (serve/handle.py) may know which OTHER replica covers the
# prompt deepest (its push-updated summary cache) at the moment it
# routes somewhere else — session affinity or load broke the tie. It
# threads that knowledge through as a __serve_peer_hint kwarg; the
# replica pops it into this thread-local and the decode tier's fabric
# rung tries the hinted peer first, saving a GCS summary query on the
# hot path. Purely advisory: a wrong/stale hint just falls through to
# the summary-derived candidates.
_peer_hint = threading.local()


def set_peer_hint(hint: Optional[Dict]):
    _peer_hint.value = hint


def _pop_peer_hint() -> Optional[Dict]:
    hint = getattr(_peer_hint, "value", None)
    _peer_hint.value = None
    return hint


# ------------------------------------------------- batched hot-prefix export
class _ExportSingleFlight:
    """Exporter-side coalescing for hot prefixes (ROADMAP item 2c): K
    concurrent ``peer_export`` calls for ONE fingerprint run one
    ``export_kv_blocks`` + one ``pack_kv_spans``; followers park on the
    leader's event and share its payload. The leader also sees every
    waiter's node id, so when the audience spans >=
    ``cfg.kv_fabric_relay_min`` distinct nodes it relays the
    pinned-arena payload through the broadcast tree (binomial fan-out,
    <= log2(K)+1 hops, ``store.broadcast`` events) instead of letting K
    importers pull point-to-point."""

    def __init__(self):
        self._lock = threading.Lock()
        self._flights: Dict[int, Dict] = {}
        self.exports = 0     # leader runs (the "exactly 1" assertion)
        self.coalesced = 0   # follower calls served from a leader's run
        self.relays = 0      # broadcast-tree relays triggered

    def run(self, key: int, fn, node_id: Optional[str] = None,
            timeout_s: float = 10.0, relay=None) -> Dict:
        with self._lock:
            fl = self._flights.get(key)
            leader = fl is None
            if leader:
                fl = {"ev": threading.Event(), "out": None, "err": None,
                      "nodes": set([node_id] if node_id else [])}
                self._flights[key] = fl
            else:
                if node_id:
                    fl["nodes"].add(node_id)
                self.coalesced += 1
        if not leader:
            if not fl["ev"].wait(timeout_s):
                raise TimeoutError("peer export single-flight timed out")
            if fl["err"] is not None:
                raise fl["err"]
            return fl["out"]
        try:
            out = fn()
            self.exports += 1
        except Exception as e:
            with self._lock:
                self._flights.pop(key, None)
            fl["err"] = e
            fl["ev"].set()
            raise
        # snapshot the audience and retire the flight BEFORE releasing
        # waiters: late arrivals start a fresh flight (the trie is warm,
        # their export is cheap) instead of racing this one's cleanup
        with self._lock:
            self._flights.pop(key, None)
            nodes = set(fl["nodes"])
        if relay is not None:
            try:
                if relay(out, nodes):
                    self.relays += 1
            except Exception:
                # the relay is an optimization: waiters can still pull
                # the ref point-to-point over the data plane
                logger.debug("hot-prefix relay failed", exc_info=True)
        fl["out"] = out
        fl["ev"].set()
        return out
class PrefillLLMDeployment(LLMDeployment):
    """Prefill-tier replica: fills KV blocks, never decodes for clients.

    ``prefill_export`` is the tier's whole API: make sure the prompt's
    chunk-aligned prefix is cached (running chunked prefill if it is
    not), export the blocks, and hand back a pinned-arena ObjectRef the
    decode tier pulls over the data plane. The engine keeps a SMALL slot
    pool (prefill scratch + the single throwaway decode step per cold
    prompt) and a LARGE prefix block pool — the inverse of a decode
    replica's shape, which is the point of disaggregating.

    Chaos: ``rpc._maybe_inject_failure("prefill_export")`` fires at
    entry and again right before the return (the mid-export death the
    ServeReplicaKiller/PrefillExportKiller suites exercise); the decode
    tier treats any failure as "fall back to local prefill"."""

    def __init__(self, model="llama-debug", *, n_slots: int = 2,
                 prefix_cache_slots: int = 8, **kw):
        if prefix_cache_slots <= 0:
            raise ValueError("the prefill tier IS its prefix cache: "
                             "prefix_cache_slots must be > 0")
        super().__init__(model, n_slots=n_slots,
                         prefix_cache_slots=prefix_cache_slots, **kw)
        self._publisher = PrefixSummaryPublisher(
            self.engine, type(self).__name__).start()

    def prefill_export(self, prompt_tokens,
                       max_chunks: Optional[int] = None) -> Dict:
        """Prefill (if needed) and export the KV blocks covering
        ``prompt_tokens``' chunk-aligned prefix. Returns ``{covered,
        chunk, ref}`` with the payload parked in the pinned arena —
        or ``{covered, chunk, payload}`` with inline bytes outside a
        cluster (direct instantiation in tests/benches)."""
        rpc._maybe_inject_failure("prefill_export")
        toks = [int(t) for t in prompt_tokens]
        eng = self.engine
        C = eng.config.prefill_chunk
        cap = (max(0, len(toks) - 1) // C if max_chunks is None
               else max(0, int(max_chunks)))
        span = events.start_span("serve.prefill_export", category="serve",
                                 prompt_tokens=len(toks))
        try:
            if cap and eng.prefix_cache.peek(toks) < cap * C:
                # cold prefix: one budgeted chunked-prefill pass fills
                # the blocks via the ordinary _populate_prefix path (the
                # single sampled token is discarded — this tier's decode
                # step exists only to complete the prefill lifecycle)
                h = eng.submit(toks, max_new_tokens=1)
                for _ in h:
                    pass
            covered, spans = eng.export_kv_blocks(toks, max_chunks=cap)
            payload = pack_kv_spans(spans)
            out: Dict[str, Any] = {"covered": covered, "chunk": C}
            try:
                import ray_tpu
                out["ref"] = ray_tpu.put(payload)
            except Exception:
                # no cluster runtime (unit tier / in-process bench):
                # inline the bytes — same framing, no data plane
                out["payload"] = payload
            rpc._maybe_inject_failure("prefill_export")
            span.set(covered=covered, payload_bytes=len(payload))
            return out
        finally:
            span.end()


# ------------------------------------------------------------ decode tier
class DisaggLLMDeployment(LLMDeployment):
    """Decode-tier replica: serves streams, never runs a long prefill
    when the cluster already has the KV.

    Admission ladder per request (see module docstring): local radix
    hit -> KV hand-off from ``prefill`` -> local chunked prefill. The
    hand-off window uses the scheduler's hold state so the request
    keeps its FIFO position while blocks are in flight; every failure
    path releases the hold, so the worst case is exactly the colocated
    path. Publishes trie summaries for cluster-wide prefix routing
    (``__serve_prefix_route__`` makes the router fingerprint incoming
    prompts and route by deepest cluster match)."""

    __serve_prefix_route__ = True

    def __init__(self, model="llama-debug", *, prefill=None,
                 handoff_timeout_s: float = 10.0,
                 prefix_cache_slots: int = 4,
                 peers: Optional[Dict[str, Any]] = None,
                 summaries_fn=None, kv_fabric: Optional[bool] = None,
                 **kw):
        super().__init__(model, prefix_cache_slots=prefix_cache_slots,
                         **kw)
        self._prefill = prefill
        self._handoff_timeout_s = float(handoff_timeout_s)
        # KV fabric (ROADMAP 2b): `peers` maps replica_id -> direct
        # object and `summaries_fn` replaces the GCS summary query —
        # both injectable so the fallback-ladder tests and the fabric
        # bench segment run hermetically, mirroring _call_prefill's
        # direct-object support. In a cluster both default to the GCS.
        self._peers = peers or {}
        self._summaries_fn = summaries_fn
        self._kv_fabric = (cfg.kv_fabric_enabled if kv_fabric is None
                           else bool(kv_fabric))
        self._singleflight = _ExportSingleFlight()
        self._publisher = PrefixSummaryPublisher(
            self.engine, type(self).__name__).start()
        from ray_tpu.util.metrics import Counter
        self._m_handoffs = Counter(
            "serve_kv_handoffs_total",
            "prefill->decode KV hand-offs by outcome",
            tag_keys=("outcome",))
        self._m_handoff_tokens = Counter(
            "serve_kv_handoff_tokens_total",
            "prompt tokens imported via KV hand-off")
        self._m_handoff_bytes = Counter(
            "serve_kv_handoff_bytes_total",
            "KV hand-off payload bytes pulled over the data plane "
            "(int8 framing roughly halves this vs fp16)")
        self._m_fabric = Counter(
            "serve_kv_fabric_total",
            "decode->decode KV fabric events by kind (peer_ok, "
            "peer_fallback, export, stale_fp, quant_mismatch, "
            "coalesced, relayed)",
            tag_keys=("kind",))

    # ------------------------------------------------- fabric: exporter
    def peer_export(self, prompt_tokens, max_chunks: Optional[int] = None,
                    want_fp: Optional[int] = None,
                    node_id: Optional[str] = None) -> Dict:
        """Serve this replica's pinned trie blocks to a PEER decode
        replica — the decode→decode half of the cluster KV fabric. Same
        contract as ``prefill_export`` (``{covered, chunk, ref|payload}``,
        int8-or-fp framing decided by this engine's kv_quant) with two
        deliberate differences: it NEVER prefills a cold prefix (a peer
        asking for tokens we don't hold should fall to its own ladder,
        not push work here), and ``want_fp`` must prove against the LIVE
        trie — a GCS summary is a push-cadence snapshot, so it can name
        blocks evicted since publication; shipping them would install KV
        for the wrong tokens on the importer. Concurrent exports of one
        fingerprint coalesce (single-flight + broadcast-tree relay)."""
        rpc._maybe_inject_failure("peer_export")
        toks = [int(t) for t in prompt_tokens]
        eng = self.engine
        C = eng.config.prefill_chunk
        cap = (max(0, len(toks) - 1) // C if max_chunks is None
               else max(0, int(max_chunks)))
        cache = eng.prefix_cache
        if cache is None or cap == 0:
            raise LookupError("nothing to export")
        live_fp = cache.covered_fp(toks, cap)
        if live_fp is None:
            self._m_fabric.inc(tags={"kind": "stale_fp"})
            raise LookupError("prefix not cached here (stale summary?)")
        if want_fp is not None and int(live_fp) != int(want_fp):
            self._m_fabric.inc(tags={"kind": "stale_fp"})
            raise LookupError(
                f"stale fingerprint: caller wants {want_fp:#x}, live "
                f"trie covers {live_fp:#x} — blocks evicted since the "
                "last summary publish")

        def _export() -> Dict:
            span = events.start_span("serve.peer_export", category="serve",
                                     prompt_tokens=len(toks))
            try:
                covered, spans = eng.export_kv_blocks(toks, max_chunks=cap)
                if not spans:
                    raise LookupError("prefix evicted under the export")
                payload = pack_kv_spans(spans)
                out: Dict[str, Any] = {"covered": covered, "chunk": C,
                                       "fp": int(live_fp)}
                try:
                    import ray_tpu
                    out["ref"] = ray_tpu.put(payload)
                except Exception:
                    out["payload"] = payload
                self._m_fabric.inc(tags={"kind": "export"})
                span.set(covered=covered, payload_bytes=len(payload))
                return out
            finally:
                span.end()

        def _relay(out: Dict, nodes: set) -> bool:
            ref = out.get("ref")
            try:
                import ray_tpu
                nodes = {n for n in nodes
                         if n and n != ray_tpu.get_runtime_context()
                         .get("node_id")}
            except Exception:
                return False
            if ref is None or len(nodes) < cfg.kv_fabric_relay_min:
                return False
            # binomial fan-out over the data plane: <= log2(K)+1 hops,
            # each arrival emits store.broadcast events the edge probe
            # asserts on. After this the waiters' ray_tpu.get(ref) is a
            # local-arena read.
            ray_tpu.broadcast_weights(ref, node_ids=sorted(nodes))
            out["relayed"] = len(nodes)
            self._m_fabric.inc(tags={"kind": "relayed"})
            return True

        out = self._singleflight.run(
            int(live_fp), _export, node_id=node_id,
            timeout_s=self._handoff_timeout_s, relay=_relay)
        rpc._maybe_inject_failure("peer_export")
        return out

    # ------------------------------------------------- fabric: importer
    def _replica_id(self) -> Optional[str]:
        try:
            import ray_tpu
            return ray_tpu.get_runtime_context().get("actor_id")
        except Exception:
            return None

    def _node_id(self) -> Optional[str]:
        try:
            import ray_tpu
            return ray_tpu.get_runtime_context().get("node_id")
        except Exception:
            return None

    def _peer_summaries(self) -> List[Dict]:
        if self._summaries_fn is not None:
            return self._summaries_fn() or []
        import ray_tpu
        return ray_tpu._get_worker().gcs_call(
            "get_prefix_summaries") or []

    def _peer_candidates(self, toks: List[int], C: int, cap: int,
                         hint: Optional[Dict]
                         ) -> List[Tuple[str, Any, int]]:
        """Peers that claim to cover this prompt, deepest first:
        ``[(replica_id, callable_peer, depth_chunks)]``. The router's
        ``__serve_peer_hint`` (if any) ranks first at its claimed depth;
        the rest come from published summaries. A replica_id without an
        injected direct object resolves to a raw ActorHandle speaking
        the replica's ``handle_request`` protocol — no controller hop."""
        from ray_tpu.inference.prefix_cache import chunk_fingerprints
        fps = chunk_fingerprints(toks, C, max_chunks=cap)
        if not fps:
            return []
        me = self._replica_id()
        ranked: List[Tuple[str, int]] = []
        seen = set()
        if hint and hint.get("replica_id") and hint["replica_id"] != me:
            d = min(cap, max(1, int(hint.get("depth") or 0) // C or cap))
            ranked.append((hint["replica_id"], d))
            seen.add(hint["replica_id"])
        try:
            rows = self._peer_summaries()
        except Exception:
            rows = []
        scored = []
        for row in rows:
            rid = row.get("replica_id")
            if not rid or rid == me or rid in seen:
                continue
            if int(row.get("chunk") or 0) != C:
                continue
            s = set(row.get("fps") or ())
            d = 0
            for j, fp in enumerate(fps):
                if fp in s:
                    d = j + 1
            if d:
                scored.append((d, rid))
        scored.sort(reverse=True)
        ranked.extend((rid, d) for d, rid in scored)
        out: List[Tuple[str, Any, int]] = []
        for rid, d in ranked:
            peer = self._peers.get(rid)
            if peer is None:
                try:
                    from ray_tpu.actor import ActorHandle
                    peer = ActorHandle(rid, ["handle_request"])
                except Exception:
                    continue
            out.append((rid, peer, d))
        return out

    def _call_peer(self, peer, toks: List[int], max_chunks: int,
                   want_fp: Optional[int]) -> Dict:
        kw = {"max_chunks": max_chunks, "want_fp": want_fp,
              "node_id": self._node_id()}
        fn = getattr(peer, "peer_export", None)
        if fn is not None and not hasattr(fn, "remote"):
            return fn(toks, **kw)            # direct object (tests/bench)
        if fn is not None and hasattr(fn, "remote"):
            return fn.remote(toks, **kw).result(
                timeout=self._handoff_timeout_s)
        # raw replica ActorHandle: speak the replica protocol
        import ray_tpu
        ref = peer.handle_request.remote("peer_export", (toks,), kw)
        return ray_tpu.get(ref, timeout=self._handoff_timeout_s)

    def _import_from_peers(self, toks: List[int], C: int, want: int,
                           hint: Optional[Dict], req_span) -> int:
        """The fabric rung: try the deepest-covering peers (at most
        two) and import whatever spans arrive. Raises when no peer
        delivers — the caller falls down the ladder."""
        eng = self.engine
        cap = want // C
        cands = self._peer_candidates(toks, C, cap, hint)
        if not cands:
            raise LookupError("no peer covers this prefix")
        from ray_tpu.inference.prefix_cache import chunk_fingerprints
        fps = chunk_fingerprints(toks, C, max_chunks=cap)
        last: Optional[Exception] = None
        for rid, peer, depth in cands[:2]:
            d = max(1, min(depth, cap, len(fps)))
            try:
                out = self._call_peer(peer, toks, d, fps[d - 1])
                if int(out.get("chunk") or 0) != C:
                    raise ValueError(
                        f"peer chunk={out.get('chunk')} != {C}")
                payload = self._fetch_payload(out)
                spans = unpack_kv_spans(payload)
                if (spans and len(spans[0]) == 4
                        and not getattr(eng, "_kv_quant", False)):
                    # int8 wire into an fp pool is the ONE lossy
                    # direction (dequantized blocks != fp-prefilled
                    # blocks); the fabric promises greedy bit-identical,
                    # so refuse and fall to local prefill. fp wire into
                    # an int8 pool quantizes with the save-path math and
                    # stays exact, so that direction imports.
                    self._m_fabric.inc(tags={"kind": "quant_mismatch"})
                    raise ValueError(
                        "quantized peer wire into fp pool; refusing "
                        "lossy import")
                covered = min(int(out["covered"]), len(spans) * C)
                if covered <= 0:
                    raise LookupError("peer export came back empty")
                imported = eng.import_kv_blocks(toks[:covered], spans)
                self._m_fabric.inc(tags={"kind": "peer_ok"})
                self._m_handoff_tokens.inc(max(0, imported))
                self._m_handoff_bytes.inc(len(payload))
                events.record_instant(
                    "serve.kv_fabric_import", category="serve",
                    trace_id=req_span.trace_id,
                    parent_span_id=req_span.span_id,
                    peer=rid, covered=covered, imported=imported,
                    payload_bytes=len(payload))
                return imported
            except Exception as e:
                last = e
                logger.debug("peer KV import from %s failed: %s", rid, e)
        raise last if last is not None else LookupError("no peer")

    # ------------------------------------------------------- hand-off
    def _call_prefill(self, toks: List[int]) -> Dict:
        p = self._prefill
        fn = getattr(p, "prefill_export", None)
        if fn is None:
            raise TypeError("prefill tier object has no prefill_export")
        if hasattr(fn, "remote"):       # DeploymentHandle method caller
            return fn.remote(toks).result(timeout=self._handoff_timeout_s)
        return fn(toks)                  # direct object (tests/benches)

    def _fetch_payload(self, out: Dict):
        if out.get("ref") is not None:
            import ray_tpu
            # the pull lands via the data plane: recv_into straight into
            # this node's arena; the returned view needs no staging copy
            return ray_tpu.get(out["ref"],
                               timeout=self._handoff_timeout_s)
        return out.get("payload")

    def _submit_request(self, prompt_tokens, max_new_tokens, temperature,
                        eos_id, deadline_s, req_span):
        eng = self.engine
        toks = [int(t) for t in prompt_tokens]
        C = eng.config.prefill_chunk
        want = (max(0, len(toks) - 1) // C) * C
        local = (eng.prefix_cache.peek(toks)
                 if eng.prefix_cache is not None else 0)
        hint = _pop_peer_hint()
        fabric = (self._kv_fabric and eng.prefix_cache is not None
                  and want > 0 and local < want)
        if ((self._prefill is None and not fabric)
                or eng.prefix_cache is None
                or want == 0 or local >= want):
            # rung 2 (local hit) or rung 5 (nothing to hand off):
            # plain colocated admission
            return super()._submit_request(
                prompt_tokens, max_new_tokens, temperature, eos_id,
                deadline_s, req_span)
        with events.trace_context(req_span.trace_id, req_span.span_id):
            handle = eng.submit(toks, max_new_tokens=max_new_tokens,
                                temperature=temperature, eos_id=eos_id,
                                deadline_s=deadline_s, hold=True)
        hspan = events.start_span(
            "serve.kv_handoff", category="serve",
            trace_id=req_span.trace_id, parent_span_id=req_span.span_id,
            prompt_tokens=len(toks), local_tokens=local)
        done = False
        try:
            # rung 3: decode→decode KV fabric — a peer replica already
            # holding the prefix serves it directly; the prefill tier
            # is no longer the only exporter in the cluster.
            if fabric:
                try:
                    imported = self._import_from_peers(toks, C, want,
                                                       hint, req_span)
                    hspan.set(source="peer", imported=imported)
                    done = True
                except Exception as e:
                    self._m_fabric.inc(tags={"kind": "peer_fallback"})
                    logger.debug("KV fabric rung failed (%s); trying "
                                 "the next rung", e)
            if not done and self._prefill is not None:
                # rung 4: the prefill tier fills cold prefixes on demand
                try:
                    out = self._call_prefill(toks)
                    if int(out.get("chunk") or 0) != C:
                        raise ValueError(
                            f"prefill tier chunk={out.get('chunk')} "
                            f"!= {C}")
                    payload = self._fetch_payload(out)
                    spans = unpack_kv_spans(payload)
                    covered = min(int(out["covered"]), len(spans) * C)
                    imported = eng.import_kv_blocks(toks[:covered], spans)
                    self._m_handoffs.inc(tags={"outcome": "ok"})
                    self._m_handoff_tokens.inc(max(0, imported))
                    self._m_handoff_bytes.inc(len(payload))
                    hspan.set(source="prefill", covered=covered,
                              imported=imported,
                              payload_bytes=len(payload))
                    done = True
                except Exception as e:
                    self._m_handoffs.inc(tags={"outcome": "fallback"})
                    logger.warning("KV hand-off failed; falling back to "
                                   "local prefill: %s", e)
                    hspan.set(error=type(e).__name__)
            if not done:
                # rung 5: local prefill. Nothing has streamed, so
                # exactly-once delivery is untouched — the request
                # simply pays the prefill it would have paid colocated.
                events.record_instant(
                    "serve.kv_handoff_fallback", category="serve",
                    trace_id=req_span.trace_id,
                    parent_span_id=req_span.span_id)
            hspan.end(ok=done)
        finally:
            eng.release_hold(handle)
        return handle


# ------------------------------------------------------------ app builder
def build_disagg_app(model="llama-debug", *, decode_replicas: int = 2,
                     prefill_replicas: int = 1,
                     prefill_kwargs: Optional[Dict] = None,
                     decode_kwargs: Optional[Dict] = None,
                     prefill_deployment_kwargs: Optional[Dict] = None,
                     decode_deployment_kwargs: Optional[Dict] = None):
    """Wire the two tiers into one Serve application graph: the decode
    tier is the ingress, bound to the prefill tier so every decode
    replica holds a handle to it. ``serve.run(build_disagg_app(...))``
    is the whole deployment story."""
    from ray_tpu import serve
    prefill = serve.deployment(
        PrefillLLMDeployment, name="prefill", tier="prefill",
        num_replicas=prefill_replicas,
        **(prefill_deployment_kwargs or {})).bind(
            model, **(prefill_kwargs or {}))
    decode = serve.deployment(
        DisaggLLMDeployment, tier="decode",
        num_replicas=decode_replicas,
        **(decode_deployment_kwargs or {})).bind(
            model, prefill=prefill, **(decode_kwargs or {}))
    return decode
