"""gRPC ingress proxy actor, one per node (reference:
python/ray/serve/_private/proxy.py gRPCProxy :558).

Schema-free generic service so users need no protoc step: requests call
``/rayserve.Ingress/Call`` with metadata ``("application", name)`` (and
optionally ``("method", name)``); request/response bodies are msgpack
(falling back to raw bytes). ``grpc_call()`` is the matching client
helper. Routing state is long-poll-pushed from the controller like the
HTTP proxy's.
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Dict, Optional

import msgpack

SERVICE = "rayserve.Ingress"
METHOD = "Call"


def _encode(obj) -> bytes:
    try:
        return msgpack.packb(obj, use_bin_type=True)
    except (TypeError, ValueError):
        import cloudpickle
        return b"\x00PKL" + cloudpickle.dumps(obj)


def _decode(data: bytes):
    if data[:4] == b"\x00PKL":
        import cloudpickle
        return cloudpickle.loads(data[4:])
    try:
        return msgpack.unpackb(data, raw=False)
    except Exception:
        return data


class _DynamicServicer:
    """Servicer stand-in handed to user `add_X_to_server` functions
    (reference: proxy.py:558 gRPCProxy — the generated registration code
    reads one attribute per proto method; every method routes into serve
    with the DESERIALIZED protobuf request as the payload, and the
    deployment returns the protobuf response message)."""

    def __init__(self, proxy: "GrpcProxy"):
        self._proxy = proxy

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        proxy = self._proxy

        def handler(request, context):
            return proxy._typed_call(method, request, context)

        return handler


class GrpcProxy:
    def __init__(self, port: int, controller, servicer_functions=None):
        import importlib

        import grpc

        self.controller = controller
        self.ingress: Dict[str, str] = {}
        self._versions = {"routes": 0}
        self._handles = {}

        proxy = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method != f"/{SERVICE}/{METHOD}":
                    return None
                return grpc.unary_unary_rpc_method_handler(
                    proxy._call,
                    request_deserializer=None,
                    response_serializer=None)

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16),
            handlers=(_Handler(),),
            # REUSEPORT off: several per-node proxies share a host in
            # tests; each must get its own distinct listener
            options=(("grpc.so_reuseport", 0),))
        # typed protobuf services (reference: grpc_servicer_functions):
        # each entry is the import path of a generated add_X_to_server;
        # protobuf (de)serialization stays in grpc's layer, the routed
        # payload is the real request message
        for path in servicer_functions or []:
            mod, _, attr = path.partition(":")
            if not attr:
                mod, attr = path.rsplit(".", 1)
            add_fn = getattr(importlib.import_module(mod), attr)
            add_fn(_DynamicServicer(self), self._server)
        try:
            bound = self._server.add_insecure_port(f"0.0.0.0:{port}")
        except RuntimeError:
            bound = 0
        if bound == 0:
            # port taken (several per-node proxies share a host in tests):
            # fall back to an ephemeral port
            bound = self._server.add_insecure_port("0.0.0.0:0")
        self._server.start()
        from ray_tpu._private.rpc import node_ip_address
        self._addr = f"{node_ip_address()}:{bound}"
        self._prime_routes()
        self._poller = threading.Thread(target=self._longpoll_loop,
                                        daemon=True)
        self._poller.start()

    def _prime_routes(self):
        from ray_tpu.serve.long_poll import prime_snapshot
        prime_snapshot(self.controller, self._versions, self._on_update)

    def _longpoll_loop(self):
        from ray_tpu.serve.long_poll import run_longpoll_loop
        run_longpoll_loop(lambda: self.controller, self._versions,
                          self._on_update)

    def _on_update(self, key: str, data):
        if key != "routes":
            return
        new_ingress = data["ingress"]
        for app, dep in list(self._handles.items()):
            if new_ingress.get(app) != dep.deployment_name:
                self._handles.pop(app, None)
        self.ingress = new_ingress

    def ready(self) -> str:
        return self._addr

    def _handle_for(self, context):
        import grpc
        meta = dict(context.invocation_metadata())
        app_name = meta.get("application", "default")
        dep = self.ingress.get(app_name)
        if dep is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no application {app_name!r}")
        h = self._handles.get(app_name)
        if h is None:
            from ray_tpu.serve.handle import DeploymentHandle
            h = DeploymentHandle(dep, app_name)
            self._handles[app_name] = h
        return h, meta

    def _call(self, request: bytes, context) -> bytes:
        import grpc
        h, meta = self._handle_for(context)
        method = meta.get("method")
        payload = _decode(request)
        try:
            target = getattr(h, method) if method else h
            result = target.remote(payload).result(timeout=60)
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{type(e).__name__}: {e}")
        return _encode(result)

    def _typed_call(self, method: str, request, context):
        """Typed-service path: the deployment method named after the
        proto rpc receives the protobuf request message and returns the
        protobuf response message."""
        import grpc
        h, _ = self._handle_for(context)
        try:
            return getattr(h, method).remote(request).result(timeout=60)
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{type(e).__name__}: {e}")


def grpc_call(address: str, payload, application: str = "default",
              method: Optional[str] = None, timeout: float = 60.0):
    """Client helper for the generic ingress."""
    import grpc

    metadata = [("application", application)]
    if method:
        metadata.append(("method", method))
    with grpc.insecure_channel(address) as channel:
        fn = channel.unary_unary(f"/{SERVICE}/{METHOD}",
                                 request_serializer=None,
                                 response_deserializer=None)
        out = fn(_encode(payload), metadata=metadata, timeout=timeout)
    return _decode(out)
