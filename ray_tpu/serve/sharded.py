"""Sharded-replica serving: one LLM replica IS a mesh gang (ROADMAP
item 1 — serve a model wider than one host "as fast as the silicon
allows").

Shape: :class:`ShardedEngineReplica` is the user callable every rank of
a ``num_hosts > 1`` deployment constructs (serve/sharded_replica.py
gang-places the ranks — PACK on commodity nodes, STRICT_SPREAD over one
slice's hosts with a ``topology`` — and joins them into one
jax.distributed world). Each rank builds the SAME model over the same
global mesh from the same seed, so the continuous-batching engine's
fixed-shape programs (prefill chunk / insert / decode or the fused
spec-decode step) are identical SPMD programs on every rank:

- rank 0 owns admission and streaming — routers hold only the rank-0
  facade; a streamed request fans out so every rank's generator drives
  the same engine step sequence (ReplicaShard.handle_stream);
- the engine runs in LOCKSTEP mode: no background decode thread — the
  request generator itself steps the engine, so the order of device
  programs is a pure function of the request stream and every rank
  stays bit-synchronized (a per-rank free-running loop would let ranks
  enter collectives in different orders and deadlock the gang);
- after each completed stream the ranks compare a digest of the tokens
  they produced (``last_stream_digest``): sampled tokens must agree
  bit-for-bit across ranks — a divergence means the SPMD invariant
  broke (non-deterministic kernel, rank-local rng drift) and the gang
  wedges itself for replacement rather than serving split-brain output
  (the GangStageHandle state-digest rule, applied to serving);
- preemption (PR 9 lifecycle) and rank death drain/replace the WHOLE
  gang: any rank's notice flips rank-0 admission off, in-flight streams
  finish, and the controller tears down every member + the placement
  group together. Severed streams re-route with ``resume_tokens`` —
  exactly-once token delivery, greedy-identical continuation.

Raw-speed multipliers (both compile-once, both optional):
``spec_decode=`` stacks draft-model speculative decoding (exactly one
extra fixed-shape verify program; greedy output bit-identical to
non-speculative serving) and ``kv_quant="int8"`` doubles+ the prefix
block count per HBM byte (inference/kv_quant.py).

Chaos: :class:`~ray_tpu.util.chaos.GangRankKiller` arms
``RAY_TPU_TESTING_RPC_FAILURE="gang_rank=p"``; a NON-ZERO rank checks
the injection hook at each engine step and SIGKILLs its own process
when it fires — the whole-gang-drain + shell-revival + stream-resume
path is asserted in tests/test_sharded_serving.py.
"""

from __future__ import annotations

import hashlib
import os
import signal
import time
from typing import Dict, Optional

from ray_tpu.inference.engine import EngineConfig, InferenceEngine


def default_serving_mesh(devices=None):
    """The sharded-serving mesh over the global device set: KV heads on
    ``tensor`` (2-way when the device count is even), the rest of the
    chips on ``fsdp`` for weight sharding — the MULTICHIP dryrun shape
    promoted to the serving plane."""
    import jax

    from ray_tpu.parallel import MeshConfig, make_mesh
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    tensor = 2 if n % 2 == 0 else 1
    return make_mesh(MeshConfig(data=1, fsdp=n // tensor, seq=1,
                                tensor=tensor), devices=devices)


class ShardedEngineReplica:
    """One rank of a mesh-gang LLM replica (see module docstring).

    Construct via ``serve.deployment(..., num_hosts=N)`` /
    :func:`build_sharded_app` — the gang machinery instantiates this on
    every rank. Single-process use (unit tests, the MULTICHIP dryrun)
    works identically: the gang is then one rank over the local
    devices.

    Engine knobs mirror :class:`LLMDeployment`; ``spec_decode`` /
    ``kv_quant`` thread through to the engine. ``mesh=None`` builds
    :func:`default_serving_mesh` over the global device set.
    """

    __serve_resumable__ = True
    __serve_coalesce_stream__ = True

    def __init__(self, model="llama-debug", *, n_slots: int = 4,
                 max_len: int = 256, prefill_chunk: int = 32,
                 prefill_budget: int = 64, eos_id: int = -1,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, params_fn=None, mesh=None,
                 seed: int = 0, prefix_cache_slots: int = 2,
                 spec_decode=None, kv_quant: str = "none",
                 stream_coalesce_tokens: int = 8,
                 stream_coalesce_ms: float = 20.0):
        import jax

        from ray_tpu.inference.api import _resolve_model
        self.model = _resolve_model(model)
        self.mesh = mesh if mesh is not None else default_serving_mesh()
        self._rank = jax.process_index()
        self._world = jax.process_count()
        self.stream_coalesce_tokens = max(1, int(stream_coalesce_tokens))
        self.stream_coalesce_ms = max(0.0, float(stream_coalesce_ms))
        params = self._build_params(params_fn, seed, max_len)
        cfg = EngineConfig(
            n_slots=n_slots, max_len=max_len, prefill_chunk=prefill_chunk,
            prefill_budget=prefill_budget, eos_id=eos_id,
            temperature=temperature, top_k=top_k, top_p=top_p,
            kv_quant=kv_quant,
            prefix_cache_slots=max(0, int(prefix_cache_slots)))
        # LOCKSTEP: the engine thread is never started — request
        # generators drive step() so every rank executes the identical
        # program sequence (module docstring)
        self.engine = InferenceEngine(self.model, params, cfg,
                                      mesh=self.mesh, seed=seed,
                                      spec=spec_decode)
        self._stream_seq = 0
        self._last_digest: Optional[tuple] = None
        self._requests_served = 0

    def _build_params(self, params_fn, seed: int, max_len: int):
        """Same-seed init on every rank gives bit-identical local
        values; under a multi-process mesh they are promoted to GLOBAL
        (replicated) arrays so the engine's jitted programs see one
        logical param tree. params_fn (checkpoint restore / weight
        arena) must already return mesh-consistent values."""
        import jax
        import numpy as np

        if params_fn is not None:
            params = params_fn()
        else:
            import jax.numpy as jnp
            tokens0 = jnp.zeros((1, min(8, max_len)), jnp.int32)
            params = self.model.init(jax.random.PRNGKey(seed),
                                     tokens0)["params"]
        if jax.process_count() > 1:
            from jax.sharding import NamedSharding, PartitionSpec
            sh = NamedSharding(self.mesh, PartitionSpec())
            params = jax.tree_util.tree_map(
                lambda x: jax.make_array_from_process_local_data(
                    sh, np.asarray(x)), params)
        return params

    # ------------------------------------------------------------ serving
    def __call__(self, prompt_tokens, max_new_tokens: int = 64,
                 temperature: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 resume_tokens=None,
                 stream_coalesce_tokens: Optional[int] = None,
                 stream_coalesce_ms: Optional[float] = None):
        """Streaming generator, coalesced-chunk protocol (lists of token
        ids; the first token is always its own eager chunk). EVERY rank
        runs this generator for every request — rank 0's chunks reach
        the client, peer ranks drain theirs (ReplicaShard streaming
        fan-out) — so the engine-stepping below is the gang's lockstep
        clock. One stream is admitted at a time (the rank-0 SPMD lock),
        which keeps the step sequence identical across ranks."""
        coalesce_n = (self.stream_coalesce_tokens
                      if stream_coalesce_tokens is None
                      else max(1, int(stream_coalesce_tokens)))
        if resume_tokens:
            # severed-stream re-route (exactly-once): the delivered
            # prefix rides the prompt through chunked prefill on the
            # replacement gang and only the continuation streams
            resume_tokens = [int(t) for t in resume_tokens]
            prompt_tokens = list(prompt_tokens) + resume_tokens
            max_new_tokens = int(max_new_tokens) - len(resume_tokens)
            if max_new_tokens <= 0:
                return
        handle = self.engine.submit(prompt_tokens,
                                    max_new_tokens=max_new_tokens,
                                    temperature=temperature, eos_id=eos_id,
                                    deadline_s=deadline_s)
        digest = hashlib.blake2b(digest_size=16)
        first = True
        pending: list = []
        try:
            for tok in self._lockstep_tokens(handle):
                digest.update(int(tok).to_bytes(4, "little", signed=True))
                pending.append(tok)
                if first:
                    yield [pending.pop(0)]
                    first = False
                elif len(pending) >= coalesce_n:
                    yield pending
                    pending = []
            if pending:
                yield pending
        except GeneratorExit:
            # client walked away mid-stream: the gang must stay in
            # lockstep, so this rank still runs the request's device
            # work to completion (peers drain theirs fully) — cancel
            # would desynchronize the program sequence
            for tok in self._lockstep_tokens(handle):
                digest.update(int(tok).to_bytes(4, "little", signed=True))
            raise
        finally:
            handle.cancel()    # no-op on a finished request
            self._stream_seq += 1
            self._last_digest = (self._stream_seq, digest.hexdigest())
            self._requests_served += 1

    def _lockstep_tokens(self, handle):
        """Drive engine.step() and yield this request's tokens as they
        emit. The chaos hook runs per step on non-zero ranks —
        GangRankKiller's SIGKILL lands mid-decode, exactly the
        rank-death the whole-gang recovery path must absorb."""
        import queue as _queue
        while True:
            self._maybe_chaos_kill()
            self.engine.step()
            while True:
                try:
                    yield handle.next(timeout=0)
                except _queue.Empty:
                    break
                except StopIteration:
                    return

    def _maybe_chaos_kill(self):
        if self._rank == 0:
            return
        from ray_tpu._private import rpc
        try:
            rpc._maybe_inject_failure("gang_rank")
        except Exception:
            os.kill(os.getpid(), signal.SIGKILL)

    def generate(self, prompt_tokens, **kw):
        """Non-streaming convenience: full token list."""
        return [t for chunk in self.__call__(prompt_tokens, **kw)
                for t in chunk]

    # ------------------------------------------------------------ control
    def last_stream_digest(self) -> Optional[tuple]:
        """(stream_seq, blake2b hex) of the tokens this rank produced
        for its most recent completed stream. ReplicaShard compares
        rank 0's against every peer's after each completed stream —
        mismatch wedges the gang (digest agreement on sampled
        tokens)."""
        return self._last_digest

    def stats(self) -> Dict:
        st = self.engine.stats()
        st["gang_rank"] = self._rank
        st["gang_world"] = self._world
        st["n_devices"] = len(self.mesh.devices.reshape(-1))
        st["requests_served"] = self._requests_served
        return st

    def begin_drain(self):
        """Preemption notice: rank 0 owns admission, so flipping the
        engine here drains the WHOLE gang — peers only ever see fanned
        requests, which stop arriving."""
        self.engine.begin_drain()

    def drain_status(self) -> Dict:
        st = self.engine.stats()
        return {"draining": st["draining"],
                "pending": st["slots_occupied"] + st["queue_depth"]}

    def check_health(self):
        # lockstep engine has no background thread to probe; draining
        # with nothing pending means this gang is retiring (controller
        # treats the gang as one unit either way)
        return True

    def on_shell_attach(self):
        """Gang-aware pre-warm (fleet shell attach): every rank runs
        this concurrently after construction, so the tiny generate
        below is itself a lockstep SPMD sequence — all fixed-shape
        programs compile on every rank before the gang is published."""
        try:
            for _ in self.__call__([1], max_new_tokens=1):
                pass
        except Exception:
            import logging
            logging.getLogger(__name__).warning(
                "sharded shell-attach warmup failed; first request "
                "will compile", exc_info=True)

    def reconfigure(self, user_config):
        if isinstance(user_config, dict) and "prefill_budget" in user_config:
            self.engine.sched.prefill_budget = max(
                1, int(user_config["prefill_budget"]))


def build_sharded_app(model="llama-debug", *, num_hosts: int = 1,
                      topology: Optional[str] = None,
                      name: str = "sharded-llm",
                      deployment_kwargs: Optional[Dict] = None,
                      **engine_kwargs):
    """One-call deployment graph for a sharded serving app:
    ``serve.run(build_sharded_app("llama-debug", num_hosts=4,
    topology="v4-32", spec_decode={...}, kv_quant="int8"))``."""
    from ray_tpu import serve
    return serve.deployment(
        ShardedEngineReplica, name=name, num_hosts=num_hosts,
        topology=topology,
        **(deployment_kwargs or {})).bind(model, **engine_kwargs)
