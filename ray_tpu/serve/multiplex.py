"""Model multiplexing: many models share a replica pool, each replica
holding an LRU cache of loaded models (reference: python/ray/serve/
multiplex.py — @serve.multiplexed + get_multiplexed_model_id; the
reference router prefers replicas that report the model loaded, here the
handle router keeps a sticky model→replica map, the cached-routing
variant of the same affinity)."""

from __future__ import annotations

import asyncio
import collections
import contextvars
import functools
import threading
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id this request was routed with."""
    return _current_model_id.get()


def _set_model_id(model_id: str):
    return _current_model_id.set(model_id)


class _ModelCache:
    """Per-wrapper LRU of loaded models; evicts with __del__ semantics."""

    def __init__(self, loader: Callable, max_models: int):
        self.loader = loader
        self.max_models = max_models
        self.models: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self.lock = threading.Lock()

    async def get(self, owner, model_id: str):
        # rtlint: disable=RT001 — bounded dict-op critical section, never
        # held across an await; sync callers (loaded_ids/__getstate__)
        # share the same threading.Lock so an asyncio.Lock can't replace it
        with self.lock:
            if model_id in self.models:
                self.models.move_to_end(model_id)
                return self.models[model_id]
        model = self.loader(owner, model_id)
        if asyncio.iscoroutine(model):
            model = await model
        # rtlint: disable=RT001 — bounded dict-op critical section (above)
        with self.lock:
            self.models[model_id] = model
            self.models.move_to_end(model_id)
            while len(self.models) > self.max_models:
                old_id, old = self.models.popitem(last=False)
                del old
        return model

    def loaded_ids(self):
        with self.lock:
            return list(self.models)

    def __getstate__(self):
        # ships with the deployment class: locks and loaded models are
        # per-replica state, recreated empty on the other side
        return {"loader": self.loader, "max_models": self.max_models}

    def __setstate__(self, state):
        self.loader = state["loader"]
        self.max_models = state["max_models"]
        self.models = collections.OrderedDict()
        self.lock = threading.Lock()


def multiplexed(_func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for a model-loader method: `async def get_model(self,
    model_id)`. Calls are cached per replica in LRU order."""

    def wrap(fn):
        cache = _ModelCache(fn, max_num_models_per_replica)

        @functools.wraps(fn)
        async def wrapper(self, model_id: Optional[str] = None):
            if model_id is None:
                model_id = get_multiplexed_model_id()
            return await cache.get(self, model_id)

        wrapper.__serve_multiplex_cache__ = cache
        return wrapper

    if _func is not None:
        return wrap(_func)
    return wrap
