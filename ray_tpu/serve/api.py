"""serve public API (reference: python/ray/serve/api.py — @deployment :240,
run :463; batching: python/ray/serve/batching.py)."""

from __future__ import annotations

import asyncio
import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.deployment import (Application, AutoscalingConfig,
                                      Deployment, DeploymentConfig)
from ray_tpu.serve.handle import DeploymentHandle

CONTROLLER_NAME = "SERVE_CONTROLLER"
_controller_handle = None


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 16,
               ray_actor_options: Optional[Dict] = None,
               autoscaling_config=None, slo_config=None,
               num_hosts: int = 1, resumable_streams: Optional[bool] = None,
               coalesce_streams: Optional[bool] = None,
               preempt_grace_s: Optional[float] = None,
               prefix_routed: Optional[bool] = None,
               tier: Optional[str] = None,
               fallback_model: Optional[str] = None,
               topology: Optional[str] = None, **_ignored):
    def wrap(target):
        # a callable opts into stream resume with __serve_resumable__ =
        # True (its streaming methods accept resume_tokens=); the
        # explicit kwarg overrides either way
        resumable = (getattr(target, "__serve_resumable__", False)
                     if resumable_streams is None else resumable_streams)
        # likewise __serve_coalesce_stream__ = True: streams yield
        # token-chunk lists that the handle layer unpacks per token
        coalesced = (getattr(target, "__serve_coalesce_stream__", False)
                     if coalesce_streams is None else coalesce_streams)
        # and __serve_prefix_route__ = True: the router fingerprints
        # prompts and routes by deepest cluster-wide trie match
        # (serve/disagg.py DisaggLLMDeployment)
        prefixed = (getattr(target, "__serve_prefix_route__", False)
                    if prefix_routed is None else prefix_routed)
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=ray_actor_options,
            num_hosts=num_hosts, topology=topology,
            resumable_streams=bool(resumable),
            coalesce_streams=bool(coalesced),
            prefix_routed=bool(prefixed), tier=tier,
            fallback_model=fallback_model)
        if preempt_grace_s is not None:
            cfg.preempt_grace_s = float(preempt_grace_s)
        if autoscaling_config is not None:
            cfg.autoscaling_config = (
                AutoscalingConfig(**autoscaling_config)
                if isinstance(autoscaling_config, dict)
                else autoscaling_config)
        if slo_config is not None:
            from ray_tpu.serve.deployment import _coerce_slo
            cfg.slo_config = _coerce_slo(slo_config)
        return Deployment(target, name or target.__name__, cfg)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def _get_controller():
    global _controller_handle
    if _controller_handle is not None:
        return _controller_handle
    try:
        _controller_handle = ray_tpu.get_actor(CONTROLLER_NAME,
                                               namespace="serve")
    except ValueError:
        from ray_tpu.serve.controller import ServeController
        actor_cls = ray_tpu.remote(ServeController)
        _controller_handle = actor_cls.options(
            name=CONTROLLER_NAME, namespace="serve", lifetime="detached",
            # long-poll listeners park one executor thread each for
            # up to 30s (proxies + handle clients); size for ~100
            # nodes of headroom. An asyncio LongPollHost would scale
            # further (reference does this) if ever needed.
            max_concurrency=256, num_cpus=0.1).remote()
    return _controller_handle


def _app_to_specs(app: Application, app_name: str) -> List[Dict]:
    import cloudpickle
    import dataclasses
    specs = []
    for node in app.flatten():
        dep = node.deployment
        cfg = dataclasses.asdict(dep.config)

        def materialize(v):
            if isinstance(v, Application):
                return DeploymentHandle(v.deployment.name, app_name)
            return v

        specs.append({
            "name": dep.name,
            "callable": cloudpickle.dumps(dep.func_or_class),
            "is_function": not isinstance(dep.func_or_class, type),
            "init_args": [materialize(a) for a in node.args],
            "init_kwargs": {k: materialize(v)
                            for k, v in node.kwargs.items()},
            "config": cfg,
        })
    return specs


_ingress: Dict[str, str] = {}          # app_name -> ingress deployment


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", blocking: bool = False,
        _http: bool = False, http_port: int = 8000) -> DeploymentHandle:
    controller = _get_controller()
    specs = _app_to_specs(app, name)
    ray_tpu.get(controller.deploy_application.remote(name, specs),
                timeout=120)
    # routes live in the controller and are long-poll-pushed to every
    # proxy (reference: EndpointState + LongPollHost)
    ray_tpu.get(controller.set_route.remote(route_prefix, name,
                                            app.deployment.name),
                timeout=30)
    _ingress[name] = app.deployment.name
    handle = DeploymentHandle(app.deployment.name, name)
    # wait for replicas
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        st = ray_tpu.get(controller.get_status.remote(), timeout=30)
        dep = st.get(name, {}).get(app.deployment.name, {})
        if dep.get("running", 0) >= 1:
            break
        time.sleep(0.2)
    if _http:
        start(http_port=http_port)
    return handle


def start(http_port: Optional[int] = None, grpc_port: Optional[int] = None,
          grpc_servicer_functions: Optional[List[str]] = None,
          wait: bool = True, timeout: float = 120.0):
    """Enable ingress: the controller keeps one HTTP (and optionally
    gRPC) proxy on every alive node (reference: proxy-per-node,
    controller ProxyState + gRPCProxy proxy.py:558). Blocks until every
    alive node has its proxies unless wait=False.
    grpc_servicer_functions: import paths of protoc-generated
    add_X_to_server functions — registers the typed protobuf services on
    every gRPC proxy (reference: gRPCOptions.grpc_servicer_functions)."""
    if http_port is None and grpc_port is None:
        http_port = 8000    # reference default: serve.start() serves HTTP
    ctrl = _get_controller()
    ray_tpu.get(ctrl.set_http.remote(http_port, grpc_port,
                                     grpc_servicer_functions), timeout=120)
    if not wait:
        return
    want_http = http_port is not None
    want_grpc = grpc_port is not None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        n_alive = len([n for n in ray_tpu.nodes() if n["alive"]])
        addrs = ray_tpu.get(ctrl.get_proxies.remote(), timeout=30)
        ok = len(addrs) >= n_alive and all(
            (not want_http or "http" in a) and (not want_grpc or "grpc" in a)
            for a in addrs.values())
        if ok and addrs:
            return
        # the reconcile lock may have skipped this round: nudge again
        ray_tpu.get(ctrl.set_http.remote(None, None), timeout=30)
        time.sleep(0.3)
    raise TimeoutError("serve ingress proxies did not come up")


def proxies() -> Dict:
    """node_id -> {"http": addr, "grpc": addr} for every ingress proxy."""
    return ray_tpu.get(_get_controller().get_proxies.remote(), timeout=30)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    ingress = _ingress.get(name)
    if ingress is None:
        st = status()
        deps = st.get(name)
        if not deps:
            raise ValueError(f"no application {name!r}")
        ingress = list(deps)[0]
    return DeploymentHandle(ingress, name)


def status() -> Dict:
    return ray_tpu.get(_get_controller().get_status.remote(), timeout=30)


def slo_status() -> Dict:
    """Latest burn-rate evaluation per declared SLO objective:
    {app: {deployment: [{objective, burn_fast, burn_slow, violating,
    ...}]}}."""
    return ray_tpu.get(_get_controller().get_slo_status.remote(),
                       timeout=30)


def fleet_status() -> Dict:
    """Fleet-plane view (serve/fleet.py): per-deployment scale-to-zero
    state, shell-pool occupancy, revival counts, and cold-start
    latency percentiles."""
    return ray_tpu.get(_get_controller().get_fleet_status.remote(),
                       timeout=30)


# ------------------------------------------------------------- tenancy
def set_tenant_quota(tenant: str, max_concurrent: Optional[int] = None,
                     weight: Optional[float] = None,
                     rate: Optional[float] = None,
                     burst: Optional[float] = None):
    """Configure one tenant's fair-share admission at the serve ingress
    (serve/fleet.py TenantAdmission; GCS ``tenant_quotas`` table):
    ``max_concurrent`` caps the tenant's in-flight requests (<= 0 =
    unlimited), ``weight`` sets its deficit-round-robin share while
    queued, ``rate`` sets the tenant's CLUSTER-WIDE admission rate in
    requests/s (<= 0 = unlimited) which the quota-lease layer splits
    proportionally across live proxies, and ``burst`` the token-bucket
    depth backing that rate (defaults to ~rate). The special tenant
    ``"__default__"`` moves the fleet-wide defaults. Proxies refresh
    quotas within ~5s; rate changes bump the lease epoch so every proxy
    re-splits within one renew interval (~2s)."""
    return ray_tpu._get_worker().gcs_call(
        "set_tenant_quota", tenant=tenant, quota=max_concurrent,
        weight=weight, rate=rate, burst=burst)


def get_tenant_quotas() -> List[Dict]:
    """Configured tenant rows: [{tenant, quota, weight, rate, burst,
    ts}]."""
    return ray_tpu._get_worker().gcs_call("get_tenant_quotas")


def quota_lease_status() -> Dict:
    """The GCS quota-lease view: {epoch, leases: [...], tenant_burn:
    {tenant: cluster-total admitted}} — the edge probe reads cluster
    burn totals from here."""
    return ray_tpu._get_worker().gcs_call("quota_lease_status")


def delete(name: str = "default"):
    ray_tpu.get(_get_controller().delete_application.remote(name),
                timeout=60)


def shutdown():
    global _controller_handle
    try:
        ctrl = _get_controller()
        for app in ray_tpu.get(ctrl.list_applications.remote(), timeout=30):
            ray_tpu.get(ctrl.delete_application.remote(app), timeout=60)
        try:
            ray_tpu.get(ctrl.shutdown_proxies.remote(), timeout=60)
        except Exception:
            pass
        ray_tpu.kill(ctrl)
    except Exception:
        pass
    _controller_handle = None
    _ingress.clear()
    from ray_tpu.serve.handle import _LongPollClient
    _LongPollClient.reset()


# ------------------------------------------------------------------ batching
def batch(_func=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Dynamic request batching for async methods (reference:
    python/ray/serve/batching.py @serve.batch). Calls buffer until the
    batch fills or the wait timeout fires, then the wrapped function runs
    once on the list of requests."""

    def wrap(fn):
        state = {"queue": None, "task": None}

        @functools.wraps(fn)
        async def wrapper(*args):
            self_arg = args[0] if len(args) == 2 else None
            item = args[-1]
            loop = asyncio.get_event_loop()
            if state["queue"] is None:
                state["queue"] = []
                state["cond"] = asyncio.Condition()

            fut = loop.create_future()
            state["queue"].append((item, fut))
            if state["task"] is None or state["task"].done():
                state["task"] = asyncio.ensure_future(
                    _flusher(self_arg, fn, state, max_batch_size,
                             batch_wait_timeout_s))
            return await fut

        return wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


async def _flusher(self_arg, fn, state, max_batch_size, wait_s):
    await asyncio.sleep(wait_s)
    while state["queue"]:
        batch_items = state["queue"][:max_batch_size]
        del state["queue"][:max_batch_size]
        items = [b[0] for b in batch_items]
        futs = [b[1] for b in batch_items]
        try:
            if self_arg is not None:
                results = await fn(self_arg, items)
            else:
                results = await fn(items)
            for f, r in zip(futs, results):
                if not f.done():
                    f.set_result(r)
        except Exception as e:
            for f in futs:
                if not f.done():
                    f.set_exception(e)
