"""serve public API (reference: python/ray/serve/api.py — @deployment :240,
run :463; batching: python/ray/serve/batching.py)."""

from __future__ import annotations

import asyncio
import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.deployment import (Application, AutoscalingConfig,
                                      Deployment, DeploymentConfig)
from ray_tpu.serve.handle import DeploymentHandle

CONTROLLER_NAME = "SERVE_CONTROLLER"
_controller_handle = None
_proxy_handle = None


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 16,
               ray_actor_options: Optional[Dict] = None,
               autoscaling_config=None, **_ignored):
    def wrap(target):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=ray_actor_options)
        if autoscaling_config is not None:
            cfg.autoscaling_config = (
                AutoscalingConfig(**autoscaling_config)
                if isinstance(autoscaling_config, dict)
                else autoscaling_config)
        return Deployment(target, name or target.__name__, cfg)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def _get_controller():
    global _controller_handle
    if _controller_handle is not None:
        return _controller_handle
    try:
        _controller_handle = ray_tpu.get_actor(CONTROLLER_NAME,
                                               namespace="serve")
    except ValueError:
        from ray_tpu.serve.controller import ServeController
        actor_cls = ray_tpu.remote(ServeController)
        _controller_handle = actor_cls.options(
            name=CONTROLLER_NAME, namespace="serve", lifetime="detached",
            max_concurrency=8, num_cpus=0.1).remote()
    return _controller_handle


def _app_to_specs(app: Application, app_name: str) -> List[Dict]:
    import cloudpickle
    import dataclasses
    specs = []
    for node in app.flatten():
        dep = node.deployment
        cfg = dataclasses.asdict(dep.config)

        def materialize(v):
            if isinstance(v, Application):
                return DeploymentHandle(v.deployment.name, app_name)
            return v

        specs.append({
            "name": dep.name,
            "callable": cloudpickle.dumps(dep.func_or_class),
            "is_function": not isinstance(dep.func_or_class, type),
            "init_args": [materialize(a) for a in node.args],
            "init_kwargs": {k: materialize(v)
                            for k, v in node.kwargs.items()},
            "config": cfg,
        })
    return specs


_ingress: Dict[str, str] = {}          # app_name -> ingress deployment
_routes: Dict[str, str] = {}           # route_prefix -> app_name


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", blocking: bool = False,
        _http: bool = False, http_port: int = 8000) -> DeploymentHandle:
    controller = _get_controller()
    specs = _app_to_specs(app, name)
    ray_tpu.get(controller.deploy_application.remote(name, specs),
                timeout=120)
    _ingress[name] = app.deployment.name
    if route_prefix:
        _routes[route_prefix] = name
    handle = DeploymentHandle(app.deployment.name, name)
    # wait for replicas
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        st = ray_tpu.get(controller.get_status.remote(), timeout=30)
        dep = st.get(name, {}).get(app.deployment.name, {})
        if dep.get("running", 0) >= 1:
            break
        time.sleep(0.2)
    if _http:
        _ensure_proxy(http_port)
    return handle


def get_app_handle(name: str = "default") -> DeploymentHandle:
    ingress = _ingress.get(name)
    if ingress is None:
        st = status()
        deps = st.get(name)
        if not deps:
            raise ValueError(f"no application {name!r}")
        ingress = list(deps)[0]
    return DeploymentHandle(ingress, name)


def status() -> Dict:
    return ray_tpu.get(_get_controller().get_status.remote(), timeout=30)


def delete(name: str = "default"):
    ray_tpu.get(_get_controller().delete_application.remote(name),
                timeout=60)


def shutdown():
    global _controller_handle, _proxy_handle
    try:
        if _proxy_handle is not None:
            ray_tpu.kill(_proxy_handle)
    except Exception:
        pass
    try:
        ctrl = _get_controller()
        for app in ray_tpu.get(ctrl.list_applications.remote(), timeout=30):
            ray_tpu.get(ctrl.delete_application.remote(app), timeout=60)
        ray_tpu.kill(ctrl)
    except Exception:
        pass
    _controller_handle = None
    _proxy_handle = None
    _ingress.clear()
    _routes.clear()


def _ensure_proxy(port: int):
    global _proxy_handle
    if _proxy_handle is not None:
        return
    from ray_tpu.serve.proxy import HttpProxy
    actor_cls = ray_tpu.remote(HttpProxy)
    _proxy_handle = actor_cls.options(
        name="SERVE_PROXY", namespace="serve", max_concurrency=64,
        num_cpus=0.1).remote(port, dict(_routes), dict(_ingress))
    ray_tpu.get(_proxy_handle.ready.remote(), timeout=60)


# ------------------------------------------------------------------ batching
def batch(_func=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Dynamic request batching for async methods (reference:
    python/ray/serve/batching.py @serve.batch). Calls buffer until the
    batch fills or the wait timeout fires, then the wrapped function runs
    once on the list of requests."""

    def wrap(fn):
        state = {"queue": None, "task": None}

        @functools.wraps(fn)
        async def wrapper(*args):
            self_arg = args[0] if len(args) == 2 else None
            item = args[-1]
            loop = asyncio.get_event_loop()
            if state["queue"] is None:
                state["queue"] = []
                state["cond"] = asyncio.Condition()

            fut = loop.create_future()
            state["queue"].append((item, fut))
            if state["task"] is None or state["task"].done():
                state["task"] = asyncio.ensure_future(
                    _flusher(self_arg, fn, state, max_batch_size,
                             batch_wait_timeout_s))
            return await fut

        return wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


async def _flusher(self_arg, fn, state, max_batch_size, wait_s):
    await asyncio.sleep(wait_s)
    while state["queue"]:
        batch_items = state["queue"][:max_batch_size]
        del state["queue"][:max_batch_size]
        items = [b[0] for b in batch_items]
        futs = [b[1] for b in batch_items]
        try:
            if self_arg is not None:
                results = await fn(self_arg, items)
            else:
                results = await fn(items)
            for f, r in zip(futs, results):
                if not f.done():
                    f.set_result(r)
        except Exception as e:
            for f in futs:
                if not f.done():
                    f.set_exception(e)
