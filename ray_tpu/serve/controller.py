"""ServeController: the serve control plane actor (reference:
python/ray/serve/_private/controller.py:84, deployment_state.py:1232
replica reconciliation, autoscaling_state.py). Holds per-application
deployment state, creates/kills replica actors, reconciles health and
autoscaling on a background thread, and serves routing tables to handles
(the reference pushes config via long-poll; here handles poll with a
version number over the same actor RPC path).

Methods are sync (they run on actor executor threads; the worker's event
loop must stay free for RPC)."""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


def autoscale_decision(auto: Dict, hist, total_load: float, target: int,
                       now: float, up_since: Dict, down_since: Dict,
                       key) -> int:
    """Pure autoscaling step (reference: serve/autoscaling_policy.py).
    Appends the sample to `hist`, windows it to look_back_period_s, and
    returns the new target: the desired count (ceil(window-avg load /
    target_ongoing_requests), clamped) applied only once the up/down
    condition has held for its delay. `up_since`/`down_since` carry the
    condition-start timestamps between calls."""
    import math
    hist.append((now, total_load))
    look = auto.get("look_back_period_s", 10.0)
    while hist and hist[0][0] < now - look:
        hist.popleft()
    avg_total = sum(v for _, v in hist) / len(hist)
    desired = math.ceil(avg_total / max(auto["target_ongoing_requests"],
                                        1e-9))
    desired = max(auto["min_replicas"], min(auto["max_replicas"], desired))
    if desired > target:
        down_since.pop(key, None)
        t0 = up_since.setdefault(key, now)
        if now - t0 >= auto.get("upscale_delay_s", 0.0):
            up_since.pop(key, None)
            return desired
    elif desired < target:
        up_since.pop(key, None)
        t0 = down_since.setdefault(key, now)
        if now - t0 >= auto.get("downscale_delay_s", 0.0):
            down_since.pop(key, None)
            return desired
    else:
        up_since.pop(key, None)
        down_since.pop(key, None)
    return target


class ServeController:
    def __init__(self):
        # apps[app][dep] = {spec, replicas: [handle], version, target}
        self.apps: Dict[str, Dict[str, Dict]] = {}
        self._lock = threading.RLock()
        self._load_hist: Dict[tuple, "collections.deque"] = {}
        self._up_since: Dict[tuple, float] = {}
        self._down_since: Dict[tuple, float] = {}
        self._stop = False
        # routing state is controller-owned so every proxy on every node
        # serves one authoritative table (reference: EndpointState +
        # ProxyState in the controller)
        self.routes: Dict[str, str] = {}        # route_prefix -> app
        self.ingress: Dict[str, str] = {}       # app -> deployment
        self.http_port: Optional[int] = None    # None = HTTP disabled
        self.grpc_port: Optional[int] = None    # None = gRPC disabled
        self._proxies: Dict[str, Any] = {}      # node_id -> actor handle
        self._grpc_proxies: Dict[str, Any] = {}
        self._proxy_addrs: Dict[str, Dict] = {} # node_id -> {http, grpc}
        # long-poll: every mutation bumps a key's version and wakes
        # listeners (reference: LongPollHost, _private/long_poll.py:177 —
        # config push instead of client polling)
        self._versions: Dict[str, int] = {"routes": 0}
        # SLO burn-rate engine (serve/slo.py): evaluated each reconcile
        # tick against the GCS time-series plane for deployments that
        # declared slo_config
        self._slo_tracker = None
        # burn-driven replica scaling (serve/slo.py BurnRateScaler):
        # one policy instance per (app, deployment)
        self._burn_scalers: Dict[tuple, Any] = {}
        self._target_gauge = None
        # fleet plane (serve/fleet.py): idle reaper + pre-warmed shell
        # pool + revival, created lazily when the first deployment opts
        # into scale-to-zero
        self._fleet = None
        # router-side prefix-summary push: the reconcile loop snapshots
        # the GCS prefix_summaries table and bumps the long-poll key on
        # change, so routers stop paying the 1 Hz pull
        self._prefix_rows: List[Dict] = []
        self._prefix_sig = None
        self._longpoll = threading.Condition()
        self._proxy_reconcile_lock = threading.Lock()
        self._thread = threading.Thread(target=self._reconcile_loop,
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------- long poll
    def _bump(self, key: str):
        with self._longpoll:
            self._versions[key] = self._versions.get(key, 0) + 1
            self._longpoll.notify_all()

    def _key_data(self, key: str):
        if key == "routes":
            return {"routes": dict(self.routes),
                    "ingress": dict(self.ingress)}
        if key.startswith("dep:"):
            _, app_name, name = key.split(":", 2)
            return self.get_deployment_info(app_name, name)
        if key == "prefix_summaries":
            return {"rows": list(self._prefix_rows)}
        return None

    def listen_for_change(self, snapshot: Dict[str, int],
                          timeout_s: float = 30.0) -> Dict[str, Dict]:
        """Block until any watched key moves past the caller's version,
        then return {key: {"version": v, "data": ...}} for the changed
        keys (empty dict on timeout — the caller just re-listens)."""
        deadline = time.monotonic() + timeout_s

        def changed():
            return {k: v for k, v in self._versions.items()
                    if k in snapshot and v > snapshot[k]}

        with self._longpoll:
            while True:
                hits = changed()
                if hits:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {}
                self._longpoll.wait(timeout=remaining)
        with self._lock:
            return {k: {"version": v, "data": self._key_data(k)}
                    for k, v in hits.items()}

    def set_route(self, route_prefix: Optional[str], app_name: str,
                  ingress_deployment: str):
        with self._lock:
            self.ingress[app_name] = ingress_deployment
            if route_prefix:
                self.routes[route_prefix] = app_name
        self._bump("routes")
        return True

    def set_http(self, port: Optional[int] = None,
                 grpc_port: Optional[int] = None,
                 grpc_servicer_functions: Optional[List[str]] = None):
        """Enable ingress: the reconcile loop keeps one HTTP (and
        optionally gRPC) proxy on every alive node (reference: proxy per
        node, controller ProxyState). grpc_servicer_functions: import
        paths of generated add_X_to_server functions registered on every
        gRPC proxy (reference: gRPCOptions.grpc_servicer_functions)."""
        stale = []
        with self._lock:
            if port is not None:
                self.http_port = port
            if grpc_port is not None:
                self.grpc_port = grpc_port
            if grpc_servicer_functions is not None:
                new = list(grpc_servicer_functions)
                if new != getattr(self, "_grpc_servicers", None):
                    self._grpc_servicers = new
                    # existing proxies were built with the old servicer
                    # list: recycle them so the reconcile below brings
                    # them back with the typed services registered
                    stale = list(self._grpc_proxies.values())
                    self._grpc_proxies.clear()
                    for addrs in self._proxy_addrs.values():
                        addrs.pop("grpc", None)
        import ray_tpu
        for p in stale:
            try:
                ray_tpu.kill(p)
            except Exception:
                pass
        self._reconcile_proxies()
        return True

    def shutdown_proxies(self):
        import ray_tpu
        with self._lock:
            proxies = list(self._proxies.values()) + \
                list(self._grpc_proxies.values())
            self._proxies.clear()
            self._grpc_proxies.clear()
            self._proxy_addrs.clear()
            self.http_port = None
            self.grpc_port = None
        for p in proxies:
            try:
                ray_tpu.kill(p)
            except Exception:
                pass
        return True

    def get_proxies(self) -> Dict[str, Dict]:
        with self._lock:
            return dict(self._proxy_addrs)

    def _reconcile_proxies(self):
        import ray_tpu
        from ray_tpu.util.scheduling_strategies import \
            NodeAffinitySchedulingStrategy
        if not self._proxy_reconcile_lock.acquire(blocking=False):
            return   # another reconcile is already creating proxies
        try:
            self._reconcile_proxies_locked(ray_tpu,
                                           NodeAffinitySchedulingStrategy)
        finally:
            self._proxy_reconcile_lock.release()

    def _reconcile_proxies_locked(self, ray_tpu,
                                  NodeAffinitySchedulingStrategy):
        with self._lock:
            http_port = self.http_port
            grpc_port = self.grpc_port
        if http_port is None and grpc_port is None:
            return
        try:
            nodes = [n for n in ray_tpu.nodes() if n["alive"]]
        except Exception:
            return
        alive_ids = {n["node_id"] for n in nodes}
        with self._lock:
            for nid in list(self._proxies):
                if nid not in alive_ids:
                    self._proxies.pop(nid, None)
                    self._proxy_addrs.pop(nid, None)
            for nid in list(self._grpc_proxies):
                if nid not in alive_ids:
                    self._grpc_proxies.pop(nid, None)
        me = ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
        for n in nodes:
            nid = n["node_id"]
            if http_port is not None and nid not in self._proxies:
                try:
                    from ray_tpu.serve.proxy import HttpProxy
                    actor_cls = ray_tpu.remote(HttpProxy)
                    proxy = actor_cls.options(
                        name=f"SERVE_PROXY:{nid[:12]}", namespace="serve",
                        max_concurrency=64, num_cpus=0.1,
                        scheduling_strategy=NodeAffinitySchedulingStrategy(
                            nid)).remote(http_port, me)
                    addr = ray_tpu.get(proxy.ready.remote(), timeout=60)
                    with self._lock:
                        self._proxies[nid] = proxy
                        self._proxy_addrs.setdefault(nid, {})["http"] = addr
                except Exception:
                    logger.exception("http proxy start failed on %s",
                                     nid[:12])
            if grpc_port is not None and nid not in self._grpc_proxies:
                try:
                    from ray_tpu.serve.grpc_proxy import GrpcProxy
                    actor_cls = ray_tpu.remote(GrpcProxy)
                    proxy = actor_cls.options(
                        name=f"SERVE_GRPC:{nid[:12]}", namespace="serve",
                        max_concurrency=64, num_cpus=0.1,
                        scheduling_strategy=NodeAffinitySchedulingStrategy(
                            nid)).remote(grpc_port, me,
                                         getattr(self, "_grpc_servicers",
                                                 None))
                    addr = ray_tpu.get(proxy.ready.remote(), timeout=60)
                    with self._lock:
                        self._grpc_proxies[nid] = proxy
                        self._proxy_addrs.setdefault(nid, {})["grpc"] = addr
                except Exception:
                    logger.exception("grpc proxy start failed on %s",
                                     nid[:12])

    def deploy_application(self, app_name: str, specs: List[Dict]):
        """specs: dependencies-first list of deployment specs."""
        builds = []
        with self._lock:
            app = self.apps.setdefault(app_name, {})
            for spec in specs:
                spec["app_name"] = app_name
                name = spec["name"]
                dep = app.get(name)
                if dep is None:
                    dep = {"spec": spec, "replicas": [], "version": 0,
                           "target": spec["config"]["num_replicas"]}
                    app[name] = dep
                else:
                    dep["spec"] = spec
                    dep["target"] = spec["config"]["num_replicas"]
                    # code/config change -> ROLLING update: bump the
                    # generation; _reconcile_deployment adds new-gen
                    # replicas first and retires old-gen ones one at a
                    # time, so capacity never drops to zero mid-deploy
                    # (reference: serve deployment_state rolling updates)
                    dep["gen"] = dep.get("gen", 0) + 1
                auto = spec["config"].get("autoscaling_config")
                if auto:
                    dep["target"] = max(auto["min_replicas"],
                                        min(dep["target"],
                                            auto["max_replicas"]))
                builds.append((dep, self._reconcile_deployment(dep)))
        # replica CONSTRUCTION runs outside the lock: a sharded gang can
        # take minutes to come up, and holding the lock would freeze the
        # whole control plane (deploys, long-poll, health) meanwhile
        for dep, n in builds:
            self._create_replicas(dep, n)
        return True

    def _build_replica(self, spec: Dict, spread_node: Optional[str] = None):
        """Construct one replica (possibly slow — sharded gangs do a
        placement-group wait + jax.distributed init + model load). MUST
        be called without self._lock held. Returns (handle, group) where
        group is the gang record for sharded replicas, else None.
        spread_node: anti-affinity hint (serve/fleet.py plan_spread) —
        soft node affinity, so a full node degrades to the default
        policy instead of failing the build."""
        import ray_tpu
        if int(spec["config"].get("num_hosts") or 1) > 1:
            # sharded replica = a gang of ReplicaShard actors; routers see
            # only the rank-0 facade, the controller keeps the group
            # record so retire/kill tears down the whole gang
            from ray_tpu.serve.sharded_replica import create_sharded_group
            return create_sharded_group(spec)
        from ray_tpu.serve.replica import Replica
        opts = dict(spec["config"].get("ray_actor_options") or {})
        max_ongoing = spec["config"].get("max_ongoing_requests", 16)
        actor_cls = ray_tpu.remote(Replica)
        a_opts = dict(
            max_concurrency=max_ongoing + 2,
            num_cpus=opts.get("num_cpus", 0.25),
            num_tpus=opts.get("num_tpus"),
            resources=opts.get("resources"))
        if opts.get("runtime_env"):
            a_opts["runtime_env"] = opts["runtime_env"]
        if spread_node:
            from ray_tpu.util.scheduling_strategies import \
                NodeAffinitySchedulingStrategy
            a_opts["scheduling_strategy"] = NodeAffinitySchedulingStrategy(
                spread_node, soft=True)
        return actor_cls.options(**a_opts).remote(
            spec["callable"], tuple(spec["init_args"]),
            spec["init_kwargs"], spec["is_function"]), None

    def _plan_spread_node(self, dep: Dict) -> Optional[str]:
        """Anti-affinity placement for the next replica of `dep`: the
        alive node hosting the fewest of this deployment's replicas, so
        one preemption/node loss can't zero a model. None on single-node
        clusters or when the cluster view is unavailable."""
        import ray_tpu
        try:
            nodes = [n for n in ray_tpu.nodes() if n.get("alive")]
        except Exception:
            return None
        with self._lock:
            node_of = dep.get("replica_nodes") or {}
            used = [node_of.get(getattr(r, "_actor_id", None))
                    for r in dep["replicas"]]
        from ray_tpu.serve.fleet import plan_spread
        return plan_spread(nodes, [u for u in used if u])

    def _create_replicas(self, dep: Dict, n: int):
        """Build `n` replicas WITHOUT holding the lock, then attach each
        under the lock — discarding it if the deployment rolled or was
        deleted while it was building."""
        if n <= 0:
            return
        from ray_tpu.serve.sharded_replica import kill_group
        import ray_tpu
        try:
            for _ in range(n):
                with self._lock:
                    spec = dep["spec"]
                    gen = dep.get("gen", 0)
                spread = self._plan_spread_node(dep)
                try:
                    handle, group = self._build_replica(spec,
                                                        spread_node=spread)
                except Exception:
                    logger.exception("replica build failed for %s/%s "
                                     "(retried next reconcile tick)",
                                     spec.get("app_name"), spec["name"])
                    break
                with self._lock:
                    app = self.apps.get(spec.get("app_name") or "", {})
                    alive = app.get(spec["name"]) is dep
                    stale = dep.get("gen", 0) != gen
                    if alive and not stale:
                        dep["replicas"].append(handle)
                        dep.setdefault("replica_gens", []).append(gen)
                        if spread:
                            dep.setdefault("replica_nodes", {})[
                                getattr(handle, "_actor_id", None)] = spread
                        if group is not None:
                            dep.setdefault("groups", {})[
                                handle._actor_id] = group
                        dep["version"] += 1
                        self._bump_dep(dep)
                        continue
                # rolled/deleted mid-build: the fresh replica is already
                # obsolete — tear it down instead of leaking it
                if group is not None:
                    kill_group(group)
                else:
                    try:
                        ray_tpu.kill(handle)
                    except Exception:
                        pass
        finally:
            with self._lock:
                dep["_creating"] = False

    def _kill_replica(self, dep: Dict, handle):
        """Kill a replica; for sharded replicas this retires the whole
        gang (every rank + the placement group)."""
        import ray_tpu
        group = (dep.get("groups") or {}).pop(
            getattr(handle, "_actor_id", None), None)
        if group is not None:
            from ray_tpu.serve.sharded_replica import kill_group
            kill_group(group)
            return
        try:
            ray_tpu.kill(handle)
        except Exception:
            pass

    def _reconcile_deployment(self, dep: Dict) -> int:
        """Caller holds self._lock. Quick mutations only (retire/drain
        bookkeeping); returns how many replicas the caller must build
        via _create_replicas OUTSIDE the lock."""
        gen = dep.get("gen", 0)
        gens = dep.setdefault("replica_gens", [])
        while len(gens) < len(dep["replicas"]):
            gens.append(gen)        # legacy/pre-roll replicas
        del gens[len(dep["replicas"]):]
        changed = False
        n_create = 0
        new_count = sum(1 for g in gens if g == gen)
        old_idx = [i for i, g in enumerate(gens) if g != gen]
        if dep.get("_creating"):
            pass        # a build is already in flight; let it land first
        elif new_count < dep["target"]:
            if old_idx:
                # mid-roll: surge ONE new-generation replica per
                # reconcile tick — gradual replacement
                n_create = 1
            else:
                # fresh deploy / plain scale-up: fill to target now
                n_create = dep["target"] - new_count
        elif old_idx:
            # current generation is at target: retire ONE old replica —
            # gracefully: routers stop picking it (version bump below),
            # the process lives until its queue drains (reference:
            # replica graceful_shutdown_wait_loop)
            victim = dep["replicas"].pop(old_idx[0])
            gens.pop(old_idx[0])
            self._start_drain(dep, victim)
            changed = True
        while len(dep["replicas"]) > dep["target"] and not old_idx:
            victim = dep["replicas"].pop()
            gens.pop()
            self._start_drain(dep, victim)
            changed = True
        if changed:
            dep["version"] += 1
            self._bump_dep(dep)
        if n_create:
            dep["_creating"] = True
        return n_create

    def _dep_key(self, dep: Dict) -> str:
        spec = dep["spec"]
        return f"dep:{spec.get('app_name', '')}:{spec['name']}"

    def _bump_dep(self, dep: Dict):
        self._bump(self._dep_key(dep))

    def _reconcile_loop(self):
        import ray_tpu
        while not self._stop:
            time.sleep(2.0)
            try:
                with self._lock:
                    items = [(a, n, dep) for a, app in self.apps.items()
                             for n, dep in app.items()]
                for app_name, name, dep in items:
                    try:
                        self._reconcile_one(app_name, name, dep)
                    except Exception:
                        # one broken deployment must not starve the
                        # others' health checks / autoscaling / proxies
                        logger.exception("reconcile failed for %s/%s",
                                         app_name, name)
                self._reconcile_proxies()
                self._fleet_tick(items)
                self._push_prefix_summaries(items)
            except Exception:
                logger.exception("reconcile loop iteration failed")

    @staticmethod
    def _wants_scale_to_zero(dep: Dict) -> bool:
        auto = dep["spec"]["config"].get("autoscaling_config") or {}
        return (int(auto.get("min_replicas", 1) or 0) == 0
                and bool(auto.get("idle_scale_to_zero_s")))

    def _fleet_mgr(self):
        if self._fleet is None:
            from ray_tpu.serve.fleet import FleetManager
            self._fleet = FleetManager(self)
        return self._fleet

    def _fleet_tick(self, items):
        """Keep the pre-warmed shell pool topped up while any deployment
        can scale to zero (off the lock; shell spawn is slow)."""
        want = any(self._wants_scale_to_zero(dep) for _, _, dep in items)
        if not want and self._fleet is None:
            return
        self._fleet_mgr().tick(want)

    def _push_prefix_summaries(self, items):
        """Satellite of ROADMAP item 1: deliver prefix_summaries to
        routers over the long-poll plane instead of their 1 Hz GCS pull.
        The reconcile tick snapshots the GCS table; a changed snapshot
        bumps the "prefix_summaries" long-poll key (routers that see no
        push fall back to pulling)."""
        from ray_tpu._private.config import cfg
        if not cfg.prefix_summary_push:
            return
        if not any(dep["spec"]["config"].get("prefix_routed")
                   for _, _, dep in items):
            return
        import ray_tpu
        try:
            rows = ray_tpu._get_worker().gcs_call("get_prefix_summaries")
        except Exception:
            return   # routers keep pulling; next tick retries
        sig = tuple(sorted(
            (r.get("replica_id"), tuple(r.get("fps") or ()))
            for r in rows or []))
        if sig == self._prefix_sig:
            return
        with self._lock:
            self._prefix_sig = sig
            self._prefix_rows = list(rows or [])
        self._bump("prefix_summaries")

    def _reconcile_one(self, app_name: str, name: str, dep: Dict):
        import ray_tpu
        alive = []
        for r in dep["replicas"]:
            try:
                # generous timeout: a slow box must not read as
                # death (kills would cascade); real deaths also
                # surface as ActorDiedError immediately
                ray_tpu.get(r.check_health.remote(), timeout=30)
                alive.append(r)
            except ray_tpu.ActorDiedError:
                logger.warning("replica of %s/%s died; replacing",
                               app_name, name)
            except Exception:
                alive.append(r)   # slow ≠ dead
        probed, states = self._probe_states(dep)
        lens = ([int(s.get("queue_len") or 0) for s in states]
                if states is not None else None)
        self._reap_draining(dep)
        # SLO evaluation talks to the GCS — keep it off the lock; the
        # rows feed both get_slo_status and the burn scaler below
        slo_rows = self._evaluate_slo(app_name, name, dep)
        dead = []
        with self._lock:
            if len(alive) != len(dep["replicas"]):
                alive_set = {id(r) for r in alive}
                dead = [r for r in dep["replicas"]
                        if id(r) not in alive_set]
                gens = dep.get("replica_gens") or []
                dep["replica_gens"] = [
                    g for r, g in zip(dep["replicas"], gens)
                    if id(r) in alive_set]
                dep["replicas"] = alive
                dep["version"] += 1
                self._bump_dep(dep)
            # preemption notices: a replica that flipped itself into
            # draining (GCE metadata / chaos channel) leaves the routing
            # table NOW and a replacement pre-starts below — the notice
            # grace, not the health checker, is its clock from here on
            if states is not None:
                for r, s in zip(probed, states):
                    if s.get("draining"):
                        self._detach_for_drain(
                            dep, r, self._preempt_grace(dep))
            self._autoscale(app_name, name, dep, lens)
            self._burn_autoscale(app_name, name, dep, slo_rows, lens)
            # idle reaper (serve/fleet.py): the ONLY path that takes the
            # last replica to zero — _autoscale floors at one
            if self._wants_scale_to_zero(dep) and not dep.get("_creating"):
                self._fleet_mgr().note_load(
                    app_name, name, dep,
                    float(sum(lens)) if lens else 0.0)
            n_create = self._reconcile_deployment(dep)
        # a dead sharded rank-0 leaves peers + a PG behind: tear the
        # gang down — OUTSIDE the lock, kill RPCs can block on slow
        # nodes (_kill_replica's groups-dict pop is GIL-atomic, same as
        # the lock-free _reap_draining / delete_application callers)
        for r in dead:
            self._kill_replica(dep, r)
        self._publish_loads(dep, lens)
        self._export_target(app_name, name, dep)
        # slow construction (sharded gangs: pg wait + jax.distributed
        # init + model load) runs on its own thread so ONE rebuilding
        # deployment never stalls the others' health checks — the
        # _creating flag keeps builds single-flight per deployment
        if n_create:
            threading.Thread(
                target=self._create_replicas, args=(dep, n_create),
                name=f"serve-build-{name}", daemon=True).start()

    def _autoscale(self, app_name, name, dep, lens=None):
        """Reference-shaped policy (serve/autoscaling_policy.py): average
        total queue depth over a look-back window, derive the DESIRED
        replica count from target_ongoing_requests, and apply it only
        after the condition has held for the up/downscale delay — bursts
        neither flap replicas up nor drain them mid-dip."""
        auto = dep["spec"]["config"].get("autoscaling_config")
        if not auto or not dep["replicas"] or lens is None \
                or len(lens) != len(dep["replicas"]):
            return
        key = (app_name, name)
        now = time.monotonic()
        hist = self._load_hist.setdefault(key, collections.deque())
        # min_replicas=0 floors at ONE replica here: only the fleet
        # manager's idle reaper (serve/fleet.py, idle_scale_to_zero_s)
        # takes the last step to zero, after the full idle window
        auto_eff = auto
        if int(auto.get("min_replicas", 1) or 0) < 1:
            auto_eff = {**auto, "min_replicas": 1}
        dep["target"] = autoscale_decision(
            auto_eff, hist, float(sum(lens)), dep["target"], now,
            self._up_since, self._down_since, key)

    def _burn_autoscale(self, app_name, name, dep, rows, lens=None):
        """Burn-driven replica scaling (serve/slo.py BurnRateScaler):
        sustained dual-window SLO burn raises dep["target"], sustained
        idle burn releases replicas — with hold + cooldown so instant
        spikes don't flap the fleet. Requires BOTH autoscaling_config
        (bounds + knobs) and slo_config (the signal). Caller holds
        self._lock."""
        auto = dep["spec"]["config"].get("autoscaling_config")
        if not auto or not rows:
            return
        from ray_tpu.serve.slo import BurnRateScaler
        key = (app_name, name)
        scaler = self._burn_scalers.setdefault(key, BurnRateScaler())
        total_load = float(sum(lens)) if lens else 0.0
        new_target = scaler.decide(auto, rows, dep["target"], total_load,
                                   time.monotonic())
        if new_target > dep["target"]:
            # burn-aware shedding (serve/fleet.py): a deployment with a
            # fallback whose replicas still have headroom sheds overflow
            # there (the handle layer routes it) instead of asking the
            # cluster autoscaler for new slices — replica churn and
            # slice acquisition are the most expensive moves a TPU
            # fleet can make
            fb_name = dep["spec"]["config"].get("fallback_model")
            fb = self.apps.get(app_name, {}).get(fb_name) \
                if fb_name else None
            if fb is not None:
                from ray_tpu.serve.fleet import fallback_has_headroom
                if fallback_has_headroom(fb):
                    if not dep.get("shed_active"):
                        from ray_tpu._private import events
                        events.record_instant(
                            "serve.burn_shed", category="serve",
                            app=app_name, deployment=name,
                            fallback=fb_name, target=dep["target"])
                        logger.info(
                            "burn shed %s/%s: overflow -> %s instead of "
                            "target %d -> %d", app_name, name, fb_name,
                            dep["target"], new_target)
                    dep["shed_active"] = True
                    return
        dep["shed_active"] = False
        if new_target == dep["target"]:
            return
        from ray_tpu._private import events
        events.record_instant(
            "serve.autoscale", category="serve", app=app_name,
            deployment=name, old_target=dep["target"],
            new_target=new_target,
            burn_slow=max((r.get("burn_slow") or 0.0 for r in rows),
                          default=0.0))
        logger.info("burn autoscale %s/%s: target %d -> %d", app_name,
                    name, dep["target"], new_target)
        dep["target"] = new_target

    def _export_target(self, app_name: str, name: str, dep: Dict):
        """serve_replica_target / serve_replica_deficit gauges: the
        autoscaler and dashboards watch the control loop's intent, not
        just its outcome."""
        if self._target_gauge is None:
            from ray_tpu.util.metrics import Gauge
            self._target_gauge = {
                "target": Gauge("serve_replica_target",
                                "replica target per deployment",
                                tag_keys=("app", "deployment")),
                "deficit": Gauge("serve_replica_deficit",
                                 "replicas wanted but not yet running",
                                 tag_keys=("app", "deployment")),
            }
        tags = {"app": app_name, "deployment": name}
        with self._lock:
            target = dep["target"]
            running = len(dep["replicas"])
        self._target_gauge["target"].set(float(target), tags=tags)
        self._target_gauge["deficit"].set(float(max(0, target - running)),
                                          tags=tags)

    def get_replica_demand(self) -> List[Dict]:
        """Unmet replica demand as resource requests — one dict per
        missing replica, shaped like a node-manager pending_demand row —
        so the cluster autoscaler (autoscaler/autoscaler.py) acquires
        TPU slices/VMs for replicas the serve control loop wants but
        cannot place yet, instead of waiting for lease-queue
        heartbeats."""
        out: List[Dict] = []
        with self._lock:
            for app in self.apps.values():
                for dep in app.values():
                    if dep.get("shed_active"):
                        # burn overflow is being shed to the fallback
                        # (serve/fleet.py): don't also bid for slices
                        continue
                    deficit = dep["target"] - len(dep["replicas"])
                    if deficit <= 0:
                        continue
                    spec = dep["spec"]
                    opts = dict(spec["config"].get("ray_actor_options")
                                or {})
                    req: Dict[str, float] = {
                        "CPU": float(opts.get("num_cpus", 0.25))}
                    if opts.get("num_tpus"):
                        req["TPU"] = float(opts["num_tpus"])
                    for k, v in (opts.get("resources") or {}).items():
                        req[k] = float(v)
                    out.extend([dict(req)] * int(deficit))
        return out

    def _start_drain(self, dep: Dict, victim,
                     timeout_s: Optional[float] = None):
        """Enroll a retired replica for graceful drain (deadline from
        the deployment's graceful_shutdown_timeout_s, default 30s;
        preemptions pass the shorter notice grace). Caller holds
        self._lock."""
        if timeout_s is None:
            timeout_s = float(dep["spec"]["config"]
                              .get("graceful_shutdown_timeout_s", 30.0))
        dep.setdefault("draining", []).append(
            (victim, time.time() + float(timeout_s)))

    def _preempt_grace(self, dep: Dict) -> float:
        return float(dep["spec"]["config"].get("preempt_grace_s", 25.0))

    def _detach_for_drain(self, dep: Dict, victim,
                          grace_s: Optional[float] = None) -> bool:
        """Remove a replica from the routing set and enroll it for
        drain — the draining replica never appears in a routing table
        again (get_deployment_info reads dep["replicas"]). Caller holds
        self._lock. Returns False when the replica already left the set
        (raced with a health-check prune or a second notice)."""
        idx = next((i for i, r in enumerate(dep["replicas"])
                    if r is victim), None)
        if idx is None:
            return False
        dep["replicas"].pop(idx)
        gens = dep.get("replica_gens") or []
        if idx < len(gens):
            gens.pop(idx)
        self._start_drain(dep, victim, grace_s)
        dep["version"] += 1
        self._bump_dep(dep)
        return True

    def preempt_replica(self, app_name: str, name: str,
                        replica_index: int = 0,
                        grace_s: Optional[float] = None) -> bool:
        """Notice-based preemption (the graceful half of spot-TPU
        economics): deliver a drain notice to one replica, drop it from
        the routing table, and pre-start its replacement immediately —
        BEFORE the kill deadline, so capacity never dips. The replica
        finishes in-flight streams; _reap_draining force-kills it at
        the grace deadline if its queue never empties."""
        import ray_tpu
        with self._lock:
            dep = self.apps.get(app_name, {}).get(name)
            if dep is None or not dep["replicas"]:
                return False
            victim = dep["replicas"][replica_index % len(dep["replicas"])]
        try:
            # outside the lock: the notice is an RPC into user code
            ray_tpu.get(victim.begin_drain.remote(), timeout=10)
        except Exception:
            # already dead or wedged — the health checker replaces it
            # through the crash path instead
            logger.warning("drain notice to %s/%s replica failed",
                           app_name, name, exc_info=True)
        with self._lock:
            if grace_s is None:
                grace_s = self._preempt_grace(dep)
            if not self._detach_for_drain(dep, victim, grace_s):
                return False
            n_create = self._reconcile_deployment(dep)
        if n_create:
            threading.Thread(
                target=self._create_replicas, args=(dep, n_create),
                name=f"serve-build-{name}", daemon=True).start()
        return True

    def _reap_draining(self, dep: Dict):
        """Kill retired replicas once their queues empty (or the drain
        deadline passes) — in-flight requests routed before the router
        saw the new replica set complete instead of dying. Probes run
        as ONE batched get outside the lock; only dead replicas or
        expired deadlines reap (slow != dead, same as health checks)."""
        import ray_tpu
        with self._lock:
            snapshot = list(dep.get("draining") or [])
        if not snapshot:
            return
        refs = [h.get_queue_len.remote() for h, _ in snapshot]
        done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=5)
        done_set = {r.id for r in done}
        victims, keep = [], []
        now = time.time()
        for (h, deadline), ref in zip(snapshot, refs):
            qlen = None
            dead = False
            if ref.id in done_set:
                try:
                    qlen = ray_tpu.get(ref, timeout=1)
                except ray_tpu.ActorDiedError:
                    dead = True
                except Exception:
                    pass
            if dead or now > deadline or qlen == 0:
                victims.append(h)
            else:
                keep.append((h, deadline))   # busy or merely slow
        for h in victims:
            self._kill_replica(dep, h)
        with self._lock:
            current = dep.get("draining") or []
            # keep anything enrolled since the snapshot + the keepers
            snap_ids = {id(h) for h, _ in snapshot}
            dep["draining"] = keep + [e for e in current
                                      if id(e[0]) not in snap_ids]

    def _probe_states(self, dep: Dict):
        """One runtime-state probe per reconcile tick, shared by
        autoscaling, the router load push, and preemption-notice pickup.
        Returns (replica_snapshot, [{"queue_len", "draining"}, ...]) or
        (None, None) when the probe failed."""
        import ray_tpu
        replicas = list(dep["replicas"])
        if not replicas:
            return None, None
        try:
            states = ray_tpu.get(
                [r.get_runtime_state.remote() for r in replicas],
                timeout=5)
            return replicas, states
        except Exception:
            return None, None

    def _publish_loads(self, dep: Dict, lens):
        """Push probed queue depths to routers: every handle then shares
        ONE load view instead of its private in-flight counts (reference:
        pow_2_scheduler probes replica queue lengths,
        replica_scheduler/pow_2_scheduler.py:52 — here the controller
        probes once and fans out over long-poll)."""
        if lens is None:
            return
        with self._lock:
            if len(lens) != len(dep["replicas"]):
                return   # replica set moved since the probe (death or
                         # drain detach): stale loads would misroute
            if lens != dep.get("loads"):
                dep["loads"] = lens
                self._bump_dep(dep)

    def _evaluate_slo(self, app_name: str, name: str, dep: Dict):
        """Burn-rate evaluation over the GCS time-series plane: exports
        slo_burn_rate/slo_violating gauges and emits slo.violation /
        slo.recovered runtime events on transitions (the signal ROADMAP
        item 2's autoscaling loop consumes)."""
        slo = (dep["spec"]["config"] or {}).get("slo_config")
        if not slo:
            return
        if self._slo_tracker is None:
            from ray_tpu.serve.slo import SloTracker
            self._slo_tracker = SloTracker()
        import ray_tpu

        def query(metric, window=60.0, agg="avg", tags=None,
                  threshold=None):
            return ray_tpu._get_worker().gcs_call(
                "query_metrics", name=metric, window=window, agg=agg,
                tags=tags, threshold=threshold)

        try:
            # per-tenant burn (ROADMAP 2d): every configured tenant gets
            # its own burn rows appended, so one tenant torching its
            # budget raises this deployment's target via BurnRateScaler
            # (and thus get_replica_demand) even while the aggregate
            # objective looks healthy
            tenants: List[str] = []
            try:
                tenants = [r["tenant"] for r in
                           (ray_tpu._get_worker()
                            .gcs_call("get_tenant_quotas") or [])
                           if r.get("tenant")
                           and r["tenant"] != "__default__"]
            except Exception:
                pass
            rows = self._slo_tracker.update(app_name, name, slo, query,
                                            tenants=tenants or None)
            with self._lock:
                dep["slo_status"] = rows
            return rows
        except Exception:
            logger.exception("SLO evaluation failed for %s/%s",
                             app_name, name)
            return None

    def get_slo_status(self) -> Dict:
        """{app: {deployment: [objective rows]}} for declared SLOs."""
        with self._lock:
            return {
                app_name: {
                    name: list(dep.get("slo_status") or [])
                    for name, dep in app.items()
                    if (dep["spec"]["config"] or {}).get("slo_config")}
                for app_name, app in self.apps.items()}

    def get_deployment_info(self, app_name: str, name: str) -> Dict:
        with self._lock:
            dep = self.apps.get(app_name, {}).get(name)
            if dep is None:
                return {"version": -1, "replicas": []}
            return {"version": dep["version"],
                    "replicas": list(dep["replicas"]),
                    "loads": list(dep.get("loads") or []),
                    "resumable": bool(dep["spec"]["config"]
                                      .get("resumable_streams")),
                    "coalesced": bool(dep["spec"]["config"]
                                      .get("coalesce_streams")),
                    # cluster-wide prefix routing (serve/disagg.py):
                    # replica actor ids key the GCS prefix_summaries
                    # rows back onto routing-table indices
                    "prefix_routed": bool(dep["spec"]["config"]
                                          .get("prefix_routed")),
                    "tier": dep["spec"]["config"].get("tier"),
                    # fleet plane (serve/fleet.py): an empty replica set
                    # on a scale_to_zero deployment makes the router
                    # hold + request revival instead of erroring; the
                    # fallback/max_ongoing pair drives overflow shedding
                    "scale_to_zero": self._wants_scale_to_zero(dep),
                    "fallback": dep["spec"]["config"]
                    .get("fallback_model"),
                    "max_ongoing": int(dep["spec"]["config"]
                                       .get("max_ongoing_requests", 0)
                                       or 0),
                    "replica_ids": [getattr(r, "_actor_id", None)
                                    for r in dep["replicas"]]}

    def revive_deployment(self, app_name: str, name: str) -> bool:
        """Router-requested cold start for a scaled-to-zero deployment
        (serve/fleet.py). Idempotent: concurrent calls while a revival
        is in flight (or once replicas exist) return True immediately —
        callers keep polling the routing table, which updates the
        moment the revived replica is published."""
        return self._fleet_mgr().revive(app_name, name)

    def get_fleet_status(self) -> Dict:
        """Fleet-plane view: per-deployment scale-to-zero state plus
        shell-pool / revival / cold-start stats."""
        with self._lock:
            deployments = {
                app_name: {
                    name: {
                        "target": dep["target"],
                        "running": len(dep["replicas"]),
                        "scale_to_zero": self._wants_scale_to_zero(dep),
                        "scaled_to_zero": (
                            self._wants_scale_to_zero(dep)
                            and dep["target"] == 0),
                        "fallback": dep["spec"]["config"]
                        .get("fallback_model"),
                        "shed_active": bool(dep.get("shed_active")),
                        "tier": dep["spec"]["config"].get("tier"),
                    }
                    for name, dep in app.items()}
                for app_name, app in self.apps.items()}
        out = {"deployments": deployments}
        if self._fleet is not None:
            out["fleet"] = self._fleet.status()
        return out

    def get_status(self) -> Dict:
        with self._lock:
            return {
                app_name: {
                    name: {"target": dep["target"],
                           "running": len(dep["replicas"]),
                           "version": dep["version"],
                           "tier": dep["spec"]["config"].get("tier")}
                    for name, dep in app.items()}
                for app_name, app in self.apps.items()}

    def list_applications(self):
        with self._lock:
            return list(self.apps.keys())

    def delete_application(self, app_name: str):
        import ray_tpu
        with self._lock:
            app = self.apps.pop(app_name, {})
            self.ingress.pop(app_name, None)
            for prefix in [p for p, a in self.routes.items()
                           if a == app_name]:
                self.routes.pop(prefix, None)
        self._bump("routes")
        for dep in app.values():
            draining = [h for h, _ in dep.get("draining") or []]
            for r in list(dep["replicas"]) + draining:
                self._kill_replica(dep, r)
        return True
