"""ServeController: the serve control plane actor (reference:
python/ray/serve/_private/controller.py:84, deployment_state.py:1232
replica reconciliation, autoscaling_state.py). Holds per-application
deployment state, creates/kills replica actors, reconciles health and
autoscaling on a background thread, and serves routing tables to handles
(the reference pushes config via long-poll; here handles poll with a
version number over the same actor RPC path).

Methods are sync (they run on actor executor threads; the worker's event
loop must stay free for RPC)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class ServeController:
    def __init__(self):
        # apps[app][dep] = {spec, replicas: [handle], version, target}
        self.apps: Dict[str, Dict[str, Dict]] = {}
        self._lock = threading.RLock()
        self._load_ema: Dict[tuple, float] = {}
        self._scale_marks: Dict[tuple, float] = {}
        self._stop = False
        self._thread = threading.Thread(target=self._reconcile_loop,
                                        daemon=True)
        self._thread.start()

    def deploy_application(self, app_name: str, specs: List[Dict]):
        """specs: dependencies-first list of deployment specs."""
        with self._lock:
            app = self.apps.setdefault(app_name, {})
            for spec in specs:
                name = spec["name"]
                dep = app.get(name)
                if dep is None:
                    dep = {"spec": spec, "replicas": [], "version": 0,
                           "target": spec["config"]["num_replicas"]}
                    app[name] = dep
                else:
                    dep["spec"] = spec
                    dep["target"] = spec["config"]["num_replicas"]
                    self._replace_replicas(dep)   # code/config change
                auto = spec["config"].get("autoscaling_config")
                if auto:
                    dep["target"] = max(auto["min_replicas"],
                                        min(dep["target"],
                                            auto["max_replicas"]))
                self._reconcile_deployment(dep)
        return True

    def _make_replica(self, spec: Dict):
        import ray_tpu
        from ray_tpu.serve.replica import Replica
        opts = dict(spec["config"].get("ray_actor_options") or {})
        max_ongoing = spec["config"].get("max_ongoing_requests", 16)
        actor_cls = ray_tpu.remote(Replica)
        return actor_cls.options(
            max_concurrency=max_ongoing + 2,
            num_cpus=opts.get("num_cpus", 0.25),
            num_tpus=opts.get("num_tpus"),
            resources=opts.get("resources"),
        ).remote(spec["callable"], tuple(spec["init_args"]),
                 spec["init_kwargs"], spec["is_function"])

    def _reconcile_deployment(self, dep: Dict):
        import ray_tpu
        changed = False
        while len(dep["replicas"]) < dep["target"]:
            dep["replicas"].append(self._make_replica(dep["spec"]))
            changed = True
        while len(dep["replicas"]) > dep["target"]:
            victim = dep["replicas"].pop()
            try:
                ray_tpu.kill(victim)
            except Exception:
                pass
            changed = True
        if changed:
            dep["version"] += 1

    def _replace_replicas(self, dep: Dict):
        import ray_tpu
        for v in dep["replicas"]:
            try:
                ray_tpu.kill(v)
            except Exception:
                pass
        dep["replicas"] = []
        dep["version"] += 1

    def _reconcile_loop(self):
        import ray_tpu
        while not self._stop:
            time.sleep(2.0)
            try:
                with self._lock:
                    items = [(a, n, dep) for a, app in self.apps.items()
                             for n, dep in app.items()]
                for app_name, name, dep in items:
                    alive = []
                    for r in dep["replicas"]:
                        try:
                            # generous timeout: a slow box must not read as
                            # death (kills would cascade); real deaths also
                            # surface as ActorDiedError immediately
                            ray_tpu.get(r.check_health.remote(), timeout=30)
                            alive.append(r)
                        except ray_tpu.ActorDiedError:
                            logger.warning("replica of %s/%s died; "
                                           "replacing", app_name, name)
                        except Exception:
                            alive.append(r)   # slow ≠ dead
                    with self._lock:
                        if len(alive) != len(dep["replicas"]):
                            dep["replicas"] = alive
                            dep["version"] += 1
                        self._autoscale(app_name, name, dep)
                        self._reconcile_deployment(dep)
            except Exception:
                logger.exception("reconcile loop iteration failed")

    def _autoscale(self, app_name, name, dep):
        import ray_tpu
        auto = dep["spec"]["config"].get("autoscaling_config")
        if not auto or not dep["replicas"]:
            return
        try:
            lens = ray_tpu.get([r.get_queue_len.remote()
                                for r in dep["replicas"]], timeout=5)
        except Exception:
            return
        key = (app_name, name)
        load = sum(lens) / max(1, len(dep["replicas"]))
        ema = 0.6 * self._load_ema.get(key, load) + 0.4 * load
        self._load_ema[key] = ema
        target = dep["target"]
        now = time.monotonic()
        mark = self._scale_marks.get(key, 0)
        if ema > auto["target_ongoing_requests"] and \
                target < auto["max_replicas"] and \
                now - mark > auto["upscale_delay_s"]:
            dep["target"] = target + 1
            self._scale_marks[key] = now
        elif ema < auto["target_ongoing_requests"] * 0.3 and \
                target > auto["min_replicas"] and \
                now - mark > auto["downscale_delay_s"]:
            dep["target"] = target - 1
            self._scale_marks[key] = now

    def get_deployment_info(self, app_name: str, name: str) -> Dict:
        with self._lock:
            dep = self.apps.get(app_name, {}).get(name)
            if dep is None:
                return {"version": -1, "replicas": []}
            return {"version": dep["version"],
                    "replicas": list(dep["replicas"])}

    def get_status(self) -> Dict:
        with self._lock:
            return {
                app_name: {
                    name: {"target": dep["target"],
                           "running": len(dep["replicas"]),
                           "version": dep["version"]}
                    for name, dep in app.items()}
                for app_name, app in self.apps.items()}

    def list_applications(self):
        with self._lock:
            return list(self.apps.keys())

    def delete_application(self, app_name: str):
        import ray_tpu
        with self._lock:
            app = self.apps.pop(app_name, {})
        for dep in app.values():
            for r in dep["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        return True
