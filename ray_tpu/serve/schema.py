"""Declarative (YAML / dict) Serve config (reference:
python/ray/serve/schema.py — ServeDeploySchema; `serve deploy config.yaml`).

Schema::

    http_options:
      port: 8000
    grpc_options:
      port: 9000
    applications:
      - name: my_app
        route_prefix: /app
        import_path: my_module:app_builder     # returns an Application
        args: {...}                            # passed to the builder
        deployments:                           # per-deployment overrides
          - name: MyDeployment
            num_replicas: 3
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional, Union


def _load_config(config: Union[str, Dict]) -> Dict:
    if isinstance(config, dict):
        return config
    import yaml
    with open(config) as f:
        return yaml.safe_load(f)


def _import_attr(path: str):
    if ":" in path:
        mod, attr = path.split(":", 1)
    else:
        mod, attr = path.rsplit(".", 1)
    target = importlib.import_module(mod)
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def deploy_from_config(config: Union[str, Dict]) -> List:
    """Deploy every application in a declarative config; returns the app
    handles in declaration order."""
    from ray_tpu.serve import api

    conf = _load_config(config)
    http = conf.get("http_options") or {}
    grpc = conf.get("grpc_options") or {}
    if http.get("port") is not None or grpc.get("port") is not None:
        api.start(http_port=http.get("port"), grpc_port=grpc.get("port"),
                  grpc_servicer_functions=grpc.get(
                      "grpc_servicer_functions"))

    handles = []
    for app_conf in conf.get("applications", []):
        name = app_conf["name"]
        builder = _import_attr(app_conf["import_path"])
        args = app_conf.get("args") or {}
        app = builder(**args) if args else (
            builder() if callable(builder) else builder)
        overrides = {d["name"]: d for d in app_conf.get("deployments", [])}
        if overrides:
            _apply_overrides(app, overrides)
        handles.append(api.run(app, name=name,
                               route_prefix=app_conf.get("route_prefix",
                                                         f"/{name}")))
    return handles


def _apply_overrides(app, overrides: Dict[str, Dict]) -> None:
    """Apply per-deployment config overrides onto a built application
    graph (num_replicas, max_ongoing_requests, ray_actor_options,
    autoscaling_config), replacing each node's Deployment in place."""
    for node in app.flatten():
        ov = overrides.get(node.deployment.name)
        if ov:
            node.deployment = node.deployment.options(
                **{k: ov[k] for k in ("num_replicas",
                                      "max_ongoing_requests",
                                      "ray_actor_options",
                                      "autoscaling_config",
                                      "num_hosts", "topology") if k in ov})
