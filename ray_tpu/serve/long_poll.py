"""Shared long-poll client loop (reference: LongPollClient,
python/ray/serve/_private/long_poll.py:64).

One protocol implementation for every listener (HTTP proxy, gRPC proxy,
handle routers): snapshot versions -> blocking listen on the controller ->
apply updates via callback -> re-listen. Errors back off and retry; a
``should_stop`` hook lets owners retire a loop when the controller
identity changes (serve.shutdown).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

LISTEN_TIMEOUT_S = 30.0
CALL_TIMEOUT_S = 60.0
ERROR_BACKOFF_S = 1.0


def run_longpoll_loop(get_controller: Callable,
                      versions: Dict[str, int],
                      on_update: Callable[[str, Dict], None],
                      should_stop: Optional[Callable[[], bool]] = None,
                      idle_sleep_s: float = 0.2) -> None:
    """Drive a long-poll listener until should_stop(). ``versions`` is
    mutated in place; ``on_update(key, data)`` is called per changed key."""
    import ray_tpu

    while not (should_stop and should_stop()):
        if not versions:
            time.sleep(idle_sleep_s)
            continue
        try:
            controller = get_controller()
            updates = ray_tpu.get(
                controller.listen_for_change.remote(dict(versions),
                                                    LISTEN_TIMEOUT_S),
                timeout=CALL_TIMEOUT_S)
        except Exception:
            time.sleep(ERROR_BACKOFF_S)
            continue
        for key, item in (updates or {}).items():
            versions[key] = item["version"]
            try:
                on_update(key, item["data"])
            except Exception:
                pass
