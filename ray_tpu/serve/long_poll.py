"""Shared long-poll client loop (reference: LongPollClient,
python/ray/serve/_private/long_poll.py:64).

One protocol implementation for every listener (HTTP proxy, gRPC proxy,
handle routers): snapshot versions -> blocking listen on the controller ->
apply updates via callback -> re-listen. Errors back off and retry; a
``should_stop`` hook lets owners retire a loop when the controller
identity changes (serve.shutdown).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

LISTEN_TIMEOUT_S = 30.0
CALL_TIMEOUT_S = 60.0
ERROR_BACKOFF_S = 1.0


def run_longpoll_loop(get_controller: Callable,
                      versions: Dict[str, int],
                      on_update: Callable[[str, Dict], None],
                      should_stop: Optional[Callable[[], bool]] = None,
                      idle_sleep_s: float = 0.2) -> None:
    """Drive a long-poll listener until should_stop(). ``versions`` is
    mutated in place; ``on_update(key, data)`` is called per changed key."""
    import ray_tpu

    while not (should_stop and should_stop()):
        if not versions:
            time.sleep(idle_sleep_s)
            continue
        try:
            controller = get_controller()
            updates = ray_tpu.get(
                controller.listen_for_change.remote(dict(versions),
                                                    LISTEN_TIMEOUT_S),
                timeout=CALL_TIMEOUT_S)
        except Exception:
            time.sleep(ERROR_BACKOFF_S)
            continue
        for key, item in (updates or {}).items():
            versions[key] = item["version"]
            try:
                on_update(key, item["data"])
            except Exception:
                pass


def prime_snapshot(controller, versions: Dict[str, int],
                   on_update: Callable[[str, Dict], None],
                   keys=("routes",), timeout: float = 30.0) -> None:
    """Synchronous initial snapshot of `keys` before the long-poll loop
    starts: a component that reports ready() must already hold state
    deployed before it came up (first-request 404 race otherwise). The
    -1 sentinel version always returns immediately (controller versions
    start at 0). Failure is logged, not raised — the loop converges."""
    import logging

    import ray_tpu
    try:
        hits = ray_tpu.get(controller.listen_for_change.remote(
            {k: -1 for k in keys}, 5.0), timeout=timeout)
        for key, item in (hits or {}).items():
            versions[key] = item["version"]
            on_update(key, item["data"])
    except Exception:
        logging.getLogger(__name__).warning(
            "initial %s snapshot failed; relying on the long-poll loop "
            "to converge (first requests may miss routes)", keys,
            exc_info=True)
