"""Deployment / Application objects (reference: python/ray/serve/api.py:240
@serve.deployment, serve/deployment.py). A Deployment is a user class (or
function) plus replica/autoscaling config; `.bind(...)` produces an
Application node whose handle-typed arguments express model composition
(reference: build_app.py graph binding)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0
    # queue-depth samples are averaged over this window before any
    # decision — bursty load doesn't flap replicas (reference:
    # serve/autoscaling_policy.py look_back_period_s)
    look_back_period_s: float = 10.0
    # burn-rate scaling knobs (serve/slo.py BurnRateScaler) — active
    # only when the deployment also declares slo_config. Dual-window
    # burn must persist burn_upscale_hold_s before the target rises;
    # burn below burn_release_threshold with per-replica load under
    # half of target_ongoing_requests for burn_downscale_idle_s
    # releases one replica; burn_cooldown_s separates actions so the
    # loop cannot flap faster than the windows refill
    burn_upscale_hold_s: float = 6.0
    burn_downscale_idle_s: float = 60.0
    burn_cooldown_s: float = 30.0
    burn_release_threshold: float = 0.25
    # scale-to-zero (serve/fleet.py): with min_replicas=0 AND this set,
    # the fleet manager reaps the LAST replica after the probed load has
    # been zero for this many seconds; the ordinary autoscaling policy
    # floors at one replica so the idle reaper is the only path to zero.
    # Revival goes through the pre-warmed shell pool on first request
    # (cold-start p99 exported as serve_cold_start_ms). None = never
    # scale to zero, even at min_replicas=0.
    idle_scale_to_zero_s: Optional[float] = None


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    ray_actor_options: Optional[Dict[str, Any]] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    # SLO objectives (serve/slo.py SloConfig): the controller evaluates
    # fast/slow-window burn rates against the GCS time-series plane and
    # exports slo_burn_rate gauges + slo.violation timeline events
    slo_config: Optional["Any"] = None
    health_check_period_s: float = 5.0
    # multi-host (slice-sharded) replicas: num_hosts > 1 makes each
    # replica a gang of ReplicaShard actors joined into one
    # jax.distributed world; topology (e.g. "v4-32") pins the gang onto
    # one healthy TPU slice, STRICT_SPREAD over its hosts
    # (serve/sharded_replica.py; SURVEY §7.2-10)
    num_hosts: int = 1
    topology: Optional[str] = None
    # streaming resume (serve/handle.py): True when the callable opted
    # in (``__serve_resumable__ = True``) — its streaming methods accept
    # ``resume_tokens=<chunks already delivered>`` and continue from
    # there, so a stream severed by replica death restarts on a
    # survivor with zero dropped or duplicated chunks
    resumable_streams: bool = False
    # coalesced streams (serve/handle.py): True when the callable opted
    # in (``__serve_coalesce_stream__ = True``) — its streaming methods
    # yield CHUNK LISTS (several tokens per frame) and the handle layer
    # unpacks them back to per-item iteration, with delivered/skip
    # accounting token-granular inside each chunk
    coalesce_streams: bool = False
    # drain deadline handed to a replica on a preemption NOTICE (GCE
    # spot TPU-VMs get ~30s between notice and kill; leave headroom for
    # the forced reap). Plain retirement keeps using
    # graceful_shutdown_timeout_s
    preempt_grace_s: float = 25.0
    graceful_shutdown_timeout_s: float = 30.0
    # cluster-wide prefix routing (serve/disagg.py): True when the
    # callable opted in (``__serve_prefix_route__ = True``) — the router
    # fingerprints each prompt's chunk-aligned prefixes and routes to
    # the replica whose published trie summary matches deepest, with
    # session-hash fallback on ties/misses
    prefix_routed: bool = False
    # burn-aware shedding (serve/fleet.py): name of a deployment in the
    # SAME application (smaller model, same API) that absorbs overflow.
    # When this deployment's replicas are saturated the handle routes
    # new requests down the fallback ladder, and the controller's burn
    # loop prefers shedding over asking the cluster autoscaler for new
    # slices while the fallback has headroom.
    fallback_model: Optional[str] = None
    # disaggregated-serving tier label ("prefill" / "decode" / None):
    # informational for status surfaces, and the unit independent
    # autoscaling operates on — each tier is its own deployment, so
    # burn-driven scaling and autoscaler binpacking size the tiers
    # separately (the tier-aware half of placement)
    tier: Optional[str] = None


def _coerce_slo(slo):
    """Accept an SloConfig or a plain dict (YAML configs)."""
    if isinstance(slo, dict):
        from ray_tpu.serve.slo import SloConfig
        return SloConfig(**slo)
    return slo


class Deployment:
    def __init__(self, func_or_class, name: str, config: DeploymentConfig):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                ray_actor_options: Optional[Dict] = None,
                autoscaling_config=None, slo_config=None,
                num_hosts: Optional[int] = None,
                fallback_model: Optional[str] = None,
                topology: Optional[str] = None) -> "Deployment":
        cfg = dataclasses.replace(self.config)
        if slo_config is not None:
            cfg.slo_config = _coerce_slo(slo_config)
        if fallback_model is not None:
            cfg.fallback_model = fallback_model
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if num_hosts is not None:
            cfg.num_hosts = num_hosts
        if topology is not None:
            cfg.topology = topology
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        if autoscaling_config is not None:
            if isinstance(autoscaling_config, dict):
                autoscaling_config = AutoscalingConfig(**autoscaling_config)
            cfg.autoscaling_config = autoscaling_config
        return Deployment(self.func_or_class, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment({self.name})"


class Application:
    """A bound deployment graph node; nested Applications in args become
    DeploymentHandles at deploy time."""

    def __init__(self, deployment: Deployment, args: Tuple, kwargs: Dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def flatten(self) -> List["Application"]:
        """All applications in this graph, dependencies first."""
        seen: List[Application] = []

        def visit(app: Application):
            for a in list(app.args) + list(app.kwargs.values()):
                if isinstance(a, Application):
                    visit(a)
            if app not in seen:
                seen.append(app)

        visit(self)
        return seen
