from ray_tpu.serve.api import (batch, deployment, get_app_handle, run,
                               shutdown, status)
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse

__all__ = ["deployment", "run", "shutdown", "status", "batch",
           "get_app_handle", "Deployment", "Application",
           "DeploymentHandle", "DeploymentResponse"]
