from ray_tpu.serve.api import (batch, delete, deployment, fleet_status,
                               get_app_handle, get_tenant_quotas, proxies,
                               run, set_tenant_quota, shutdown, slo_status,
                               start, status)
from ray_tpu.serve.grpc_proxy import grpc_call
from ray_tpu.serve.schema import deploy_from_config
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.sharded import ShardedEngineReplica, build_sharded_app

__all__ = ["deployment", "run", "shutdown", "status", "batch", "delete",
           "get_app_handle", "Deployment", "Application",
           "DeploymentHandle", "DeploymentResponse", "multiplexed",
           "get_multiplexed_model_id", "start", "proxies", "grpc_call",
           "deploy_from_config", "slo_status", "fleet_status",
           "set_tenant_quota", "get_tenant_quotas",
           "ShardedEngineReplica", "build_sharded_app"]
