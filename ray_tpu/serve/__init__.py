from ray_tpu.serve.api import (batch, delete, deployment, get_app_handle,
                               run, shutdown, status)
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed

__all__ = ["deployment", "run", "shutdown", "status", "batch", "delete",
           "get_app_handle", "Deployment", "Application",
           "DeploymentHandle", "DeploymentResponse", "multiplexed",
           "get_multiplexed_model_id"]
