"""DeploymentHandle + client-side power-of-two-choices routing.

Reference: python/ray/serve/handle.py:745 (DeploymentHandle),
_private/replica_scheduler/pow_2_scheduler.py:52. The router here is
embedded in the handle (no proxy hop for handle calls): it tracks its own
in-flight count per replica and picks the less-loaded of two random
replicas — the cached-queue-length variant of P2C."""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu


class DeploymentResponse:
    """Future for one deployment call."""

    def __init__(self, ref, router=None, replica_id=None, resubmit=None):
        self._ref = ref
        self._router = router
        self._replica_id = replica_id
        self._resubmit = resubmit
        self._done = False

    def result(self, timeout: Optional[float] = None):
        try:
            return ray_tpu.get(self._ref, timeout=timeout)
        except Exception as e:
            # the replica died after accepting the call (e.g. retired
            # mid-roll before the router refreshed) or refused it while
            # draining: re-route ONCE through the handle against the
            # current replica set
            if not _is_replica_death(e):
                raise
            self._settle()
            if self._resubmit is None:
                raise
            retry, self._resubmit = self._resubmit, None
            return retry().result(timeout=timeout)
        finally:
            self._settle()

    def _settle(self):
        if not self._done and self._router is not None:
            self._done = True
            self._router._dec(self._replica_id)

    def __await__(self):
        async def _get():
            try:
                from ray_tpu._private.worker import global_worker
                return await global_worker.core.get_async(self._ref)
            except ray_tpu.ActorDiedError:
                self._settle()
                if self._resubmit is None:
                    raise
                retry, self._resubmit = self._resubmit, None
                return await retry()
            finally:
                self._settle()
        return _get().__await__()

    @property
    def _object_ref(self):
        return self._ref

    def __del__(self):
        self._settle()


def _is_replica_death(e: BaseException) -> bool:
    """Failures that mean THIS replica is gone (re-routable), as opposed
    to an application error the caller must see. A draining replica
    (preemption notice won the race against the routing-table update)
    counts: it refuses the call at the boundary, before side effects."""
    from ray_tpu.serve.replica import ReplicaDrainingError
    return isinstance(e, (ray_tpu.ActorDiedError, ray_tpu.ObjectLostError,
                          ray_tpu.WorkerCrashedError,
                          ReplicaDrainingError))


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment call: a thin value-fetching
    wrapper around the core ObjectRefGenerator — chunks arrive as the
    replica's generator yields, with the core protocol's backpressure
    (round-5; reference: DeploymentResponseGenerator, serve/handle.py).

    Coalesced deployments (``__serve_coalesce_stream__``) yield LISTS of
    items per wire frame; with ``unpack=True`` this wrapper buffers each
    frame and hands items out one at a time, so the public per-item
    iteration is identical while the handle→router→replica round-trip
    amortizes over the whole frame. ``next_batch()`` exposes the frame
    boundary for egress paths (the proxy writes a frame's NDJSON lines
    in one syscall). All delivery/dedupe accounting below is
    ITEM-granular — a resume mid-frame never drops or duplicates the
    frame's tail.

    Replica death mid-stream re-routes ONCE, like the unary
    DeploymentResponse: ``resume(fetched, items)`` (installed by the
    handle) restarts the stream on the current replica set. Resumable
    deployments get the fetched items back as ``resume_tokens`` and
    continue in place; non-resumable ones restart from scratch and this
    wrapper discards the first ``fetched`` items — either way the
    consumer sees every item exactly once."""

    def __init__(self, ref_gen, router, replica_idx, resume=None,
                 record_chunks: bool = False, unpack: bool = False):
        self._gen = ref_gen
        self._router = router
        self._idx = replica_idx
        self._got_first = False
        self._resume = resume
        self._unpack = unpack
        self._buf: List = []          # fetched-but-undelivered items
        self._delivered = 0           # items handed to the consumer
        self._fetched = 0             # items pulled off the wire
        # fetched items, kept only for resumable deployments (they are
        # token ids there — small); non-resumable re-routes dedupe by
        # count alone. Buffered items count as fetched: on a resume they
        # are still delivered from the buffer, so the fresh stream must
        # continue AFTER them.
        self._chunks: Optional[List] = [] if record_chunks else None

    def __iter__(self):
        return self

    def _fetch(self):
        """One wire frame off the underlying ref generator, unpacked to
        a list of items (StopIteration at end of stream). Split out so
        the resume path and the skip-ahead dedupe share it."""
        # 60s liveness bound: a replica generator wedged in user
        # code surfaces a TimeoutError instead of hanging the caller
        ref = self._gen.next(timeout=60)
        value = self._get(ref)
        if self._unpack and isinstance(value, (list, tuple)):
            return list(value)
        return [value]

    @staticmethod
    def _get(ref):
        return ray_tpu.get(ref, timeout=60)

    def _fill_buf(self):
        """Fetch the next non-empty frame into the buffer, re-routing
        once on replica death. Raises StopIteration at end of stream."""
        while not self._buf:
            try:
                items = self._fetch()
            except StopIteration:
                self._settle()
                raise
            except Exception as e:
                if self._resume is None or not _is_replica_death(e):
                    self._settle()
                    raise
                resume, self._resume = self._resume, None   # one-shot
                try:
                    fresh, skip = resume(self._fetched, self._chunks)
                    self._adopt(fresh, skip)
                except StopIteration:
                    self._settle()
                    raise
                except Exception:
                    self._settle()
                    raise e   # surface the ORIGINAL death, not the retry
                continue
            self._fetched += len(items)
            if self._chunks is not None:
                self._chunks.extend(items)
            self._buf.extend(items)

    def __next__(self):
        self._fill_buf()
        if not self._got_first:
            # client-observed first chunk (TTFT as the CALLER saw it,
            # network + queueing included — the engine-side first-token
            # instant measures the same moment from the other end)
            self._got_first = True
            from ray_tpu._private import events
            events.record_instant("serve.first_chunk", category="serve")
        self._delivered += 1
        return self._buf.pop(0)

    def next_batch(self) -> List:
        """Drain everything currently buffered (at least one item,
        fetching a frame if needed) in one call — the coalesced-egress
        counterpart of __next__. Raises StopIteration at end of
        stream."""
        self._fill_buf()
        if not self._got_first:
            self._got_first = True
            from ray_tpu._private import events
            events.record_instant("serve.first_chunk", category="serve")
        batch, self._buf = self._buf, []
        self._delivered += len(batch)
        return batch

    def _adopt(self, fresh: "DeploymentResponseGenerator", skip: int):
        """Take over a freshly routed stream: steal its underlying
        generator + routing slot (neutering the donor so its __del__
        doesn't decrement our in-flight count), then discard the first
        `skip` items — the ones a non-resumable restart re-produces.
        Item-granular: a restart frame that straddles the skip boundary
        keeps its tail."""
        self._settle()
        self._gen = fresh._gen
        self._idx = fresh._idx
        self._router = fresh._router
        fresh._router = None
        while skip > 0:
            items = self._fetch()
            if len(items) > skip:
                self._buf.extend(items[skip:])
                self._fetched += len(items) - skip
                if self._chunks is not None:
                    self._chunks.extend(items[skip:])
                skip = 0
            else:
                skip -= len(items)

    def _settle(self):
        if self._router is not None:
            self._router._dec(self._idx)
            self._router = None

    def close(self):
        """Walk away mid-stream: stops the replica-side generator (its
        finally/GeneratorExit path runs, freeing whatever the stream
        held — e.g. an inference-engine slot), drops unconsumed chunks,
        and releases this handle's in-flight routing count."""
        try:
            self._gen.close()
        except Exception:
            pass
        self._settle()

    def __del__(self):
        # only the routing count here: the underlying ObjectRefGenerator
        # closes itself (non-blocking) in its own __del__
        self._settle()


class _LongPollClient:
    """One background listener per process pushing controller config into
    registered routers (reference: LongPollClient, long_poll.py:64 —
    replaces interval polling; the 2s refresh in _Router stays as a
    fallback when the controller is unreachable)."""

    _instance = None
    _lock = threading.Lock()

    @classmethod
    def get(cls) -> "_LongPollClient":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls):
        """Serve shutdown: stop the listener so a later serve session (new
        controller identity) starts a fresh client instead of a thread
        stuck talking to a dead actor."""
        with cls._lock:
            inst = cls._instance
            cls._instance = None
        if inst is not None:
            inst._stopped = True

    def __init__(self):
        self._routers: Dict[str, List] = {}
        self._summary_routers: Dict[str, List] = {}
        self._versions: Dict[str, int] = {}
        self._reg_lock = threading.Lock()
        self._stopped = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def register(self, router: "_Router"):
        key = f"dep:{router.app_name}:{router.deployment_name}"
        with self._reg_lock:
            self._routers.setdefault(key, []).append(router)
            self._versions.setdefault(key, -1)

    def watch_summaries(self, router: "_Router"):
        """Subscribe a prefix-routed router to pushed prefix summaries
        (the controller bumps the "prefix_summaries" key when the GCS
        table changes — ROADMAP item 1's push satellite). Idempotent."""
        with self._reg_lock:
            lst = self._summary_routers.setdefault("prefix_summaries", [])
            if router not in lst:
                lst.append(router)
            self._versions.setdefault("prefix_summaries", -1)

    def _loop(self):
        from ray_tpu.serve.long_poll import run_longpoll_loop

        def get_controller():
            from ray_tpu.serve.api import _get_controller
            return _get_controller()

        def on_update(key, data):
            if key == "prefix_summaries":
                with self._reg_lock:
                    routers = list(self._summary_routers.get(key, []))
                for r in routers:
                    r._apply_summary_push((data or {}).get("rows") or [])
                return
            with self._reg_lock:
                routers = list(self._routers.get(key, []))
            for r in routers:
                r._apply_push(data)

        run_longpoll_loop(get_controller, self._versions, on_update,
                          should_stop=lambda: self._stopped)


class _Router:
    def __init__(self, deployment_name: str, app_name: str):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self.replicas: List = []        # actor handles
        self.inflight: Dict[int, int] = {}
        self.shared_load: Dict[int, int] = {}  # controller-probed depths
        self.version = -1
        self.resumable = False   # deployment streams accept resume_tokens
        self.coalesced = False   # streams yield token-chunk lists
        # cluster-wide prefix routing (serve/disagg.py): the deployment
        # opted in, replica_ids key the GCS prefix_summaries rows onto
        # routing indices, and _summaries caches {replica_id: fp set}
        self.prefix_routed = False
        self.replica_ids: List = []
        self._summaries: Dict[str, set] = {}
        self._summary_chunk: Optional[int] = None
        self._last_summary_refresh = 0.0
        self._summary_push_t = 0.0    # last long-poll summary push
        self._watching_summaries = False
        # fleet plane (serve/fleet.py): scale-to-zero deployments hold
        # callers instead of erroring on an empty replica set; fallback
        # + max_ongoing drive overflow shedding down the fallback ladder
        self.scale_to_zero = False
        self.fallback: Optional[str] = None
        self.max_ongoing = 0
        self._revive_t = 0.0          # last revive request (throttle)
        self.lock = threading.Lock()
        self._last_refresh = 0.0
        self.model_map: Dict[str, int] = {}   # multiplexed model -> replica
        try:
            _LongPollClient.get().register(self)
        except Exception:
            pass   # push is an optimization; polling still works

    def _ingest(self, info: Dict, now: float):
        """Fold one controller get_deployment_info payload in (shared by
        the long-poll push and the polling refresh). Caller holds
        self.lock."""
        self._last_refresh = now
        self.resumable = bool(info.get("resumable"))
        self.coalesced = bool(info.get("coalesced"))
        self.prefix_routed = bool(info.get("prefix_routed"))
        self.replica_ids = list(info.get("replica_ids") or [])
        self.scale_to_zero = bool(info.get("scale_to_zero"))
        self.fallback = info.get("fallback") or None
        self.max_ongoing = int(info.get("max_ongoing") or 0)
        if info["version"] != self.version:
            self.version = info["version"]
            self.replicas = info["replicas"]
            self.inflight = {i: 0 for i in range(len(self.replicas))}
            self.model_map.clear()
        self.shared_load = dict(enumerate(info.get("loads") or []))

    def _watch_summaries_once(self):
        if self._watching_summaries:
            return
        self._watching_summaries = True
        try:
            _LongPollClient.get().watch_summaries(self)
        except Exception:
            pass   # pull fallback still works

    def _apply_push(self, info: Dict):
        with self.lock:
            self._ingest(info, time.monotonic())
            prefix = self.prefix_routed
        if prefix:
            self._watch_summaries_once()

    def _controller(self):
        from ray_tpu.serve.api import _get_controller
        return _get_controller()

    def refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and self.replicas and now - self._last_refresh < 2.0:
            return
        info = ray_tpu.get(self._controller().get_deployment_info.remote(
            self.app_name, self.deployment_name), timeout=30)
        with self.lock:
            self._ingest(info, now)
            prefix = self.prefix_routed
        if prefix:
            self._watch_summaries_once()

    def _apply_summary_push(self, rows: List[Dict]):
        """Prefix summaries arriving over the long-poll plane (the
        controller snapshots the GCS table each reconcile tick). While
        pushes keep coming the 1 Hz GCS pull is suppressed — the push
        path replaces it, it doesn't stack on top."""
        summaries: Dict[str, set] = {}
        chunk = None
        for row in rows or []:
            summaries[row["replica_id"]] = set(row.get("fps") or ())
            chunk = chunk or int(row.get("chunk") or 0)
        with self.lock:
            mine = set(r for r in self.replica_ids if r)
            self._summaries = {rid: s for rid, s in summaries.items()
                               if not mine or rid in mine}
            self._summary_chunk = chunk or None
            self._summary_push_t = time.monotonic()

    def _refresh_summaries(self):
        """Pull the GCS prefix_summaries rows for this deployment's
        replicas (throttled to 1 Hz; the rows themselves refresh at
        cfg.prefix_summary_interval_s and expire at the TTL). Skipped
        while long-poll pushes are fresh (_apply_summary_push) — the
        pull is the fallback for when the push plane is unavailable.
        Failure just leaves routing on the session-hash/P2C rungs."""
        now = time.monotonic()
        from ray_tpu._private.config import cfg
        if now - getattr(self, "_summary_push_t", 0.0) < 2.0 * max(
                1.0, cfg.prefix_summary_interval_s):
            return
        if now - self._last_summary_refresh < 1.0:
            return
        self._last_summary_refresh = now
        try:
            rows = ray_tpu._get_worker().gcs_call(
                "get_prefix_summaries",
                ids=[r for r in self.replica_ids if r] or None)
        except Exception:
            return
        summaries: Dict[str, set] = {}
        chunk = None
        for row in rows or []:
            summaries[row["replica_id"]] = set(row.get("fps") or ())
            chunk = chunk or int(row.get("chunk") or 0)
        with self.lock:
            self._summaries = summaries
            self._summary_chunk = chunk or None

    def _cluster_match_depths(self, prompt_tokens, n: int) -> Dict[int, int]:
        """{replica_idx: matched chunk depth} over the cached summaries:
        depth d means the replica's published trie covers the prompt's
        first d chunks. Pure set intersections — no tokens leave the
        client, no RPC on this path."""
        if not self._summaries or not self._summary_chunk:
            return {}
        from ray_tpu.inference.prefix_cache import chunk_fingerprints
        C = self._summary_chunk
        # same cap as engine admission: the last token always prefills
        fps = chunk_fingerprints(
            [int(t) for t in prompt_tokens], C,
            max_chunks=max(0, (len(prompt_tokens) - 1) // C))
        if not fps:
            return {}
        depths: Dict[int, int] = {}
        for i in range(n):
            rid = self.replica_ids[i] if i < len(self.replica_ids) else None
            s = self._summaries.get(rid)
            if not s:
                continue
            d = 0
            for j, fp in enumerate(fps):
                if fp in s:
                    d = j + 1
            if d:
                depths[i] = d
        return depths

    def overloaded(self) -> bool:
        """True when every replica sits at (or past) its
        max_ongoing_requests — the shed trigger for deployments with a
        fallback_model. A zero-replica set counts as overloaded (there
        is nothing to serve; a scale-to-zero revival may be warming in
        parallel). max_ongoing unknown (0) never reads overloaded."""
        with self.lock:
            n = len(self.replicas)
            if n == 0:
                return True
            if not self.max_ongoing:
                return False
            load = sum(self.shared_load.get(i, 0)
                       + self.inflight.get(i, 0) for i in range(n))
            return load >= n * self.max_ongoing

    def _request_revive(self):
        """Ask the controller to cold-start this deployment (throttled
        to 1/s; the revival itself is idempotent controller-side)."""
        now = time.monotonic()
        if now - self._revive_t < 1.0:
            return
        self._revive_t = now
        try:
            ray_tpu.get(self._controller().revive_deployment.remote(
                self.app_name, self.deployment_name), timeout=10)
        except Exception:
            pass   # the next poll retries

    def _hold_for_revival(self):
        """Handle-level hold queue (serve/fleet.py; the analog of the
        scheduler's ``submit(hold=)`` remote-prefill state): callers of
        a scaled-to-zero deployment park HERE — request submitted zero
        times — while the fleet manager attaches a pre-warmed shell.
        They release the moment the revived replica is published to the
        routing table, so every held request is dispatched exactly
        once, to a replica that actually exists. Returns when replicas
        appear; on timeout the caller falls through to the ordinary
        no-replica error."""
        from ray_tpu._private.config import cfg
        deadline = time.monotonic() + cfg.fleet_cold_start_timeout_s
        while time.monotonic() < deadline:
            with self.lock:
                if self.replicas:
                    return
            self._request_revive()
            time.sleep(0.1)
            try:
                self.refresh(force=True)
            except Exception:
                pass   # controller briefly unreachable: keep holding

    def pick(self, model_id: str = "", session_id: str = "",
             avoid: Optional[set] = None, prompt_tokens=None,
             hint_out: Optional[Dict] = None):
        self.refresh()
        if self.prefix_routed and prompt_tokens is not None:
            self._refresh_summaries()
        if not self.replicas and getattr(self, "scale_to_zero", False):
            self._hold_for_revival()
        with self.lock:
            n = len(self.replicas)
            if n == 0:
                raise RuntimeError(
                    f"deployment {self.deployment_name} has no replicas")
            score = lambda i: (self.shared_load.get(i, 0)  # noqa: E731
                               + self.inflight.get(i, 0))
            avoid = avoid or set()
            prefix_depths: Dict[int, int] = {}
            if self.prefix_routed and prompt_tokens is not None \
                    and not model_id:
                prefix_depths = {
                    i: d for i, d in
                    self._cluster_match_depths(prompt_tokens, n).items()
                    if i not in (avoid or set())}
            if model_id and self.model_map.get(model_id, n) < n:
                # sticky multiplex routing: the replica that loaded this
                # model keeps serving it (reference: multiplexed replica
                # preference in the pow-2 scheduler)
                idx = self.model_map[model_id]
            elif prefix_depths:
                # cluster-wide longest-prefix routing (ROADMAP 1c): the
                # replica whose published trie summary covers the prompt
                # deepest serves it — N private caches act as one. Ties
                # break to session affinity when the sticky replica is
                # among the deepest, else to the least-loaded of them.
                best = max(prefix_depths.values())
                winners = [i for i, d in prefix_depths.items()
                           if d == best]
                if session_id:
                    import zlib
                    sticky = zlib.crc32(str(session_id).encode()) % n
                    if sticky in winners:
                        idx = sticky
                    else:
                        idx = min(winners, key=score)
                else:
                    idx = min(winners, key=score)
            elif session_id:
                # session affinity (ROADMAP 1c): hash the session onto a
                # sticky replica so repeat prompts land where their
                # prefix KV is cached. Draining replicas are detached
                # from `replicas` by the controller, so the hash only
                # ever lands on live ones; if the sticky pick already
                # failed this call (stale view: drained/died under us),
                # fall back to least-ongoing among the others.
                import zlib
                idx = zlib.crc32(str(session_id).encode()) % n
                if idx in avoid:
                    rest = [i for i in range(n) if i not in avoid]
                    if rest:
                        idx = min(rest, key=score)
            elif n == 1:
                idx = 0
            else:
                # P2C on the SHARED load signal (controller-probed queue
                # depth, pushed over long-poll) plus this handle's own
                # in-flight count — many independent handles converge on
                # one view instead of each degrading toward random
                # (reference: pow_2_scheduler.py:52 queue-length probes)
                cand = [i for i in range(n) if i not in avoid] \
                    or list(range(n))
                if len(cand) >= 2:
                    a, b = random.sample(cand, 2)
                    idx = a if score(a) <= score(b) else b
                else:
                    idx = cand[0]
            if model_id:
                self.model_map[model_id] = idx
            if hint_out is not None and prefix_depths:
                # KV-fabric peer hint (serve/disagg.py): routing landed
                # somewhere OTHER than the deepest-covering replica
                # (session affinity / load / avoid broke the tie) — tell
                # the chosen replica who holds the prefix so its fabric
                # rung skips the GCS summary query
                best = max(((d, i) for i, d in prefix_depths.items()
                            if i != idx), default=None)
                if best is not None and best[0] > prefix_depths.get(idx, 0):
                    d, i = best
                    rid = (self.replica_ids[i]
                           if i < len(self.replica_ids) else None)
                    if rid:
                        hint_out["peer"] = {
                            "replica_id": rid,
                            "depth": d * (self._summary_chunk or 0)}
            self.inflight[idx] = self.inflight.get(idx, 0) + 1
            return idx, self.replicas[idx]

    def _dec(self, idx):
        with self.lock:
            if idx in self.inflight and self.inflight[idx] > 0:
                self.inflight[idx] -= 1


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._invoke(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default"):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._router = _Router(deployment_name, app_name)

    def _invoke(self, method: str, args, kwargs,
                retry: int = 2,
                allow_resubmit: bool = True,
                shed_depth: int = 0) -> DeploymentResponse:
        # burn-aware shedding (serve/fleet.py): a saturated deployment
        # with a fallback_model hands NEW requests down the fallback
        # ladder (each rung may shed again; depth-capped so a cycle in
        # the ladder cannot loop). Resubmits of accepted requests never
        # shed — exactly-once stays with the original deployment.
        if allow_resubmit:
            shed = self._maybe_shed(method, args, kwargs, retry,
                                    shed_depth)
            if shed is not None:
                return shed
        # unwrap nested responses so replicas receive resolved values
        args = tuple(a._object_ref if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: (v._object_ref if isinstance(v, DeploymentResponse)
                      else v) for k, v in kwargs.items()}
        model_id = getattr(self, "_model_id", "")
        if model_id:
            kwargs = {**kwargs, "__serve_model_id": model_id}
        tenant = getattr(self, "_tenant", "")
        if tenant:
            # fair-share routing metadata (serve/fleet.py): the replica
            # pops it; proxy-side admission enforces quotas
            kwargs = {**kwargs, "__serve_tenant": tenant}
        session_id = getattr(self, "_session_id", "")
        stream = getattr(self, "_stream", False)
        # prefix-routed deployments (serve/disagg.py): the prompt is the
        # streaming call's first positional arg — fingerprint it so the
        # router can match against the cluster's published trie
        # summaries. Anything non-tokenlike just skips the rung.
        prompt = None
        if self._router.prefix_routed and args \
                and method in ("__call__", "generate"):
            try:
                prompt = [int(t) for t in args[0]]
            except (TypeError, ValueError):
                prompt = None
        last_err = None
        avoid: set = set()    # replicas that already failed this call
        from ray_tpu._private import events
        for _ in range(retry + 1):
            hint_out: Optional[Dict] = {} if prompt is not None else None
            with events.record_span("serve.route", category="serve",
                                    deployment=self.deployment_name,
                                    app=self.app_name) as route_span:
                idx, replica = self._router.pick(model_id, session_id,
                                                 avoid,
                                                 prompt_tokens=prompt,
                                                 hint_out=hint_out)
                route_span.set(replica=idx)
            call_kwargs = kwargs
            if hint_out and hint_out.get("peer"):
                call_kwargs = {**kwargs,
                               "__serve_peer_hint": hint_out["peer"]}
            try:
                if stream:
                    ref_gen = replica.handle_stream.options(
                        num_returns="streaming").remote(
                            method, args, call_kwargs)
                    resume = None
                    if allow_resubmit:
                        resume = self._make_stream_resume(method, args,
                                                          kwargs, retry)
                    return DeploymentResponseGenerator(
                        ref_gen, self._router, idx, resume=resume,
                        record_chunks=self._router.resumable,
                        unpack=self._router.coalesced)
                ref = replica.handle_request.remote(method, args,
                                                    call_kwargs)
                # one resubmit only: the retried response carries NO
                # further resubmit, so a crash loop surfaces instead of
                # retrying unboundedly past the caller's timeout
                resub = None
                if allow_resubmit:
                    resub = lambda: (  # noqa: E731
                        self._router.refresh(force=True)
                        or self._invoke(method, args, kwargs, retry=retry,
                                        allow_resubmit=False))
                return DeploymentResponse(ref, self._router, idx,
                                          resubmit=resub)
            except Exception as e:
                self._router._dec(idx)
                avoid.add(idx)
                self._router.refresh(force=True)
                last_err = e
        raise last_err

    MAX_SHED_DEPTH = 4

    def _maybe_shed(self, method, args, kwargs, retry, shed_depth):
        """One rung of the fallback ladder: when this deployment is
        saturated (router.overloaded()) and declares a fallback_model,
        route the request there instead of queueing into the overload.
        Returns None to serve locally. A scaled-to-zero primary also
        kicks its revival here, so the fallback absorbs traffic WHILE
        the primary warms — burn-aware shedding's whole point."""
        r = self._router
        if not r.fallback or shed_depth >= self.MAX_SHED_DEPTH:
            return None
        try:
            r.refresh()
        except Exception:
            return None
        if not r.overloaded():
            return None
        if r.scale_to_zero and not r.replicas:
            r._request_revive()
        from ray_tpu.serve.fleet import record_fallback_shed
        record_fallback_shed(self.deployment_name, r.fallback,
                             app=self.app_name)
        return self._fallback_handle()._invoke(
            method, args, kwargs, retry=retry,
            shed_depth=shed_depth + 1)

    def _fallback_handle(self) -> "DeploymentHandle":
        fb = getattr(self, "_fb_handle", None)
        if fb is None or fb.deployment_name != self._router.fallback:
            fb = DeploymentHandle(self._router.fallback, self.app_name)
            self._fb_handle = fb
        # carry the caller's traits (stream/session/tenant/model) down
        # the ladder so the fallback serves the same call shape
        return fb.options(
            multiplexed_model_id=getattr(self, "_model_id", ""),
            stream=getattr(self, "_stream", False),
            session_id=getattr(self, "_session_id", ""),
            tenant=getattr(self, "_tenant", ""))

    def _make_stream_resume(self, method, args, kwargs, retry):
        """One-shot re-route for a stream severed by replica death (the
        streaming counterpart of DeploymentResponse's resubmit). Returns
        (fresh DeploymentResponseGenerator, chunks_to_skip): resumable
        deployments receive the delivered chunks as resume_tokens and
        continue from the exact next position (skip 0); non-resumable
        ones restart the stream internally and the caller skips the
        first `delivered` chunks so the client never sees a duplicate."""
        def resume(delivered: int, chunks):
            self._router.refresh(force=True)
            if self._router.resumable and chunks is not None:
                kw = dict(kwargs)
                prior = list(kw.pop("resume_tokens", None) or [])
                kw["resume_tokens"] = prior + list(chunks)
                return self._invoke(method, args, kw, retry=retry,
                                    allow_resubmit=False), 0
            return self._invoke(method, args, kwargs, retry=retry,
                                allow_resubmit=False), delivered
        return resume

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._invoke("__call__", args, kwargs)

    def __getattr__(self, name: str):
        if name.startswith("_") or name in ("deployment_name", "app_name"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def options(self, *, multiplexed_model_id: str = "",
                stream: bool = False, session_id: str = "",
                tenant: str = "", **_kw) -> "DeploymentHandle":
        if not multiplexed_model_id and not stream and not session_id \
                and not tenant:
            return self
        clone = DeploymentHandle(self.deployment_name, self.app_name)
        clone._router = self._router          # share routing state
        if multiplexed_model_id:
            clone._model_id = multiplexed_model_id
        if session_id:
            # sticky-session routing: calls through this handle hash to
            # one replica so repeat prompts hit its prefix cache
            clone._session_id = str(session_id)
        if tenant:
            # fair-share admission identity (serve/fleet.py): the
            # HTTP-header analog is X-RayTPU-Tenant at the proxy
            clone._tenant = str(tenant)
        # a handle derived twice (options().options()) keeps its traits
        clone._stream = stream or getattr(self, "_stream", False)
        if not session_id and getattr(self, "_session_id", ""):
            clone._session_id = self._session_id
        if not multiplexed_model_id and getattr(self, "_model_id", ""):
            clone._model_id = self._model_id
        if not tenant and getattr(self, "_tenant", ""):
            clone._tenant = self._tenant
        return clone

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name, self.app_name))
