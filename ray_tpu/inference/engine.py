"""Continuous-batching inference engine: a fixed-shape KV slot pool and
a persistent decode loop.

Architecture (the TPU-serving shape — cf. slot-based continuous
batching in the Gemma-on-TPU serving stack):

- The engine owns ``n_slots`` KV-cache slots, allocated once as
  ``[n_layers, n_slots, max_len, Hkv, D]`` per-layer stacked arrays and
  donated through every step, so the decode step compiles exactly ONCE
  and then mutates the pool in place for the life of the engine.
- Each iteration of the loop (a) admits queued prompts via *chunked
  prefill* under a per-step prefill-token budget — a long prompt is
  split into fixed-shape chunks that run through the cached-attention
  path (``chunked_prefill=True``) into a scratch cache, so admission
  never stalls in-flight decodes for more than ``prefill_budget``
  tokens of work — and (b) advances EVERY occupied slot one token in a
  single batched decode step (per-slot ``idx`` vector: each row attends
  and writes at its own length).
- Tokens stream out per request through ``RequestHandle`` queues;
  slots are evicted (and immediately reusable) on EOS, max-tokens,
  slot-capacity, cancellation, or deadline.

Shapes are static everywhere — tokens [n_slots], lengths [n_slots],
prompt chunks [1, prefill_chunk] — so XLA compiles three programs
(prefill chunk, slot insert, decode step) and nothing ever recompiles
across admissions/evictions. ``decode_compile_count`` counts decode
retraces; tests assert it stays at 1.

Sampling is shared with ``make_generate_fn`` via models/sampling.py:
greedy engine output is bit-identical to the one-program generator.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


@functools.lru_cache(maxsize=None)
def _sharded_zeros(sharding):
    """Jitted zeros with an explicit output sharding, memoized per
    sharding (jit caches per (shape, dtype) static args underneath).
    Allocating through jit is what makes the result a GLOBAL array when
    the mesh spans multiple processes — a host-side ``jnp.zeros`` +
    ``device_put`` only ever produces a single-process value."""
    import jax
    import jax.numpy as jnp
    return jax.jit(jnp.zeros, static_argnums=(0, 1),
                   out_shardings=sharding)

from ray_tpu._private import events
from ray_tpu.inference.scheduler import (FINISH_LENGTH, PrefillChunk,
                                         Request, RequestHandle,
                                         RequestState, Scheduler)


@dataclasses.dataclass
class EngineConfig:
    """Knobs of the slot pool and admission policy.

    n_slots: decode batch width (slots advance together every step).
    max_len: per-slot KV capacity (prompt + generated tokens).
    prefill_chunk: static shape of one prefill call; prompts are split
        into chunks of exactly this many tokens (last chunk padded).
    prefill_budget: max prompt tokens admitted per engine step — the
        knob that trades TTFT (higher = prompts land faster) against
        inter-token latency of in-flight decodes (lower = decode steps
        between prefill work come sooner).
    eos_id: default EOS (<0 disables); per-request override on Request.
    temperature/top_k/top_p: default sampling (temperature has a
        per-request override; top_k/top_p are compiled in).
    """
    n_slots: int = 4
    max_len: int = 512
    prefill_chunk: int = 64
    prefill_budget: int = 64
    eos_id: int = -1
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    cache_dtype: Any = None       # default: model activation dtype
    # prefix-block quantization (kv_quant.py): "int8" stores the BLOCK
    # pool as int8 values + fp32 per-(position, head) scale rows —
    # ~itemsize*D/(D+4) more cached chunks per HBM byte, and the disagg
    # hand-off ships the same compressed spans. The decode slot pool
    # stays full precision (it is transient and donated through the hot
    # program). The miss path write-throughs each completed chunk and
    # reloads the dequantized values, so greedy output stays
    # bit-identical between a prefix-cache hit and the miss that
    # populated it.
    kv_quant: str = "none"
    # radix/prefix KV cache (prefix_cache.py): extra cache-only slots of
    # the SAME [n_layers, 1, max_len, Hkv, D] shape as decode slots,
    # carved into prefill_chunk-aligned blocks that hold completed
    # prefill spans. 0 disables. Admission with a trie hit copies the
    # matched blocks instead of re-running prefill over them; the copy
    # programs are fixed-shape, so the compile-once invariant holds.
    prefix_cache_slots: int = 0
    # per-step time/FLOP attribution (util/profiling.py): emits
    # runtime_decode_step_mfu + compute/host-gap/data-wait phase gauges;
    # the observability-overhead bench toggles this off for its baseline
    step_profile: bool = True


class InferenceEngine:
    """Continuous-batching engine over one model + params (optionally on
    a parallel mesh: params stay wherever the caller sharded them; the
    KV pool shards batch (slots) over the data axes and KV heads over
    `tensor`, same as make_generate_fn's cache)."""

    def __init__(self, model, params, config: Optional[EngineConfig] = None,
                 mesh=None, rules=None, seed: int = 0, spec=None):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.params = params
        self.config = config or EngineConfig()
        self.mesh = mesh
        self._rules = rules
        cfg = self.config
        mcfg = model.cfg
        from ray_tpu.inference import spec_decode as spec_lib
        from ray_tpu.inference.kv_quant import check_mode
        self._kv_quant = check_mode(cfg.kv_quant) == "int8"
        # speculative decoding (spec_decode.py): both slot pools grow by
        # k positions so the fixed [len, len+k+1) verify write window
        # never clamps back onto live entries
        self._spec = spec_lib.resolve_spec(spec)
        self._spec_k = self._spec.k if self._spec is not None else 0
        self._pool_len = cfg.max_len + self._spec_k
        if self._pool_len > mcfg.max_seq_len:
            raise ValueError(
                f"max_len={cfg.max_len} (+ spec k={self._spec_k}) exceeds "
                f"the model's max_seq_len={mcfg.max_seq_len}")
        self._draft_model = self._draft_params = None
        if self._spec is not None:
            self._draft_model, self._draft_params = spec_lib.resolve_draft(
                self._spec, mcfg)
            if self._pool_len > self._draft_model.cfg.max_seq_len:
                raise ValueError(
                    f"draft max_seq_len={self._draft_model.cfg.max_seq_len}"
                    f" < max_len + k = {self._pool_len}")
        self.prefix_cache = None
        if cfg.prefix_cache_slots > 0:
            from ray_tpu.inference.prefix_cache import RadixPrefixCache
            self._blocks_per_slot = cfg.max_len // cfg.prefill_chunk
            self.prefix_cache = RadixPrefixCache(
                cfg.prefill_chunk,
                cfg.prefix_cache_slots * self._blocks_per_slot)
        self.sched = Scheduler(cfg.n_slots, cfg.prefill_budget,
                               default_temperature=cfg.temperature,
                               eos_id=cfg.eos_id,
                               chunk_size=cfg.prefill_chunk,
                               prefix_cache=self.prefix_cache)
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._rng = jax.random.PRNGKey(seed)

        dtype = cfg.cache_dtype or mcfg.dtype
        pool_shape = (mcfg.n_layers, cfg.n_slots, self._pool_len,
                      mcfg.n_kv_heads, mcfg.head_dim)
        # scratch is prefill_chunk longer than a slot so a padded final
        # chunk can never clamp its write window back onto real entries
        self._scratch_len = cfg.max_len + cfg.prefill_chunk
        self._scratch_shape = (mcfg.n_layers, 1, self._scratch_len,
                               mcfg.n_kv_heads, mcfg.head_dim)
        self._pool_sharding = None
        self._target_pool_shape = pool_shape
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ray_tpu.parallel import sharding as sharding_lib
            from ray_tpu.parallel.train_step import (_prune_indivisible,
                                                     logical_pspec_to_mesh)
            rules = rules or sharding_lib.DEFAULT_RULES
            spec = _prune_indivisible(
                logical_pspec_to_mesh(
                    P(None, "batch", None, "kv_heads", None), rules),
                pool_shape, mesh)
            self._pool_sharding = NamedSharding(mesh, spec)
        self._pool_k = self._zeros(pool_shape, dtype)
        self._pool_v = self._zeros(pool_shape, dtype)
        self._cache_dtype = dtype
        self._fp_itemsize = int(jnp.dtype(dtype).itemsize)
        self._dpool_k = self._dpool_v = None
        self._draft_scratch_shape = None
        if self._spec is not None:
            # draft slot pool: same layout as the target's (incl. the k
            # padding), replicated — the draft is small by design and
            # its scan runs inside the one fused program
            dcfg = self._draft_model.cfg
            dshape = (dcfg.n_layers, cfg.n_slots, self._pool_len,
                      dcfg.n_kv_heads, dcfg.head_dim)
            dsh = None
            if mesh is not None:
                # same logical layout as the target pool, pruned against
                # the DRAFT shape (its kv-head count may not divide the
                # tensor axis)
                from jax.sharding import (NamedSharding,
                                          PartitionSpec as P)

                from ray_tpu.parallel import sharding as sharding_lib
                from ray_tpu.parallel.train_step import (
                    _prune_indivisible, logical_pspec_to_mesh)
                drules = self._rules or sharding_lib.DEFAULT_RULES
                dsh = NamedSharding(mesh, _prune_indivisible(
                    logical_pspec_to_mesh(
                        P(None, "batch", None, "kv_heads", None), drules),
                    dshape, mesh))
            self._dpool_k = self._zeros(dshape, dtype, sharding=dsh)
            self._dpool_v = self._zeros(dshape, dtype, sharding=dsh)
            self._draft_scratch_shape = (
                dcfg.n_layers, 1, self._scratch_len, dcfg.n_kv_heads,
                dcfg.head_dim)
        self._blocks_k = self._blocks_v = None
        self._blocks_ks = self._blocks_vs = None
        if self.prefix_cache is not None:
            # block storage: prefix_cache_slots more rows of the same
            # per-slot shape, replicated (blocks are read via copies
            # into the replicated scratch cache, never attended over
            # in place, so they need no batch sharding). kv_quant="int8"
            # stores int8 values + fp32 per-(position, head) scale rows.
            bdtype = jnp.int8 if self._kv_quant else dtype
            block_shape = (mcfg.n_layers, cfg.prefix_cache_slots,
                           cfg.max_len, mcfg.n_kv_heads, mcfg.head_dim)
            rsh = None
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                rsh = NamedSharding(mesh, PartitionSpec())
            self._blocks_k = self._zeros(block_shape, bdtype, sharding=rsh)
            self._blocks_v = self._zeros(block_shape, bdtype, sharding=rsh)
            if self._kv_quant:
                scale_shape = block_shape[:-1]
                self._blocks_ks = self._zeros(scale_shape, jnp.float32,
                                              sharding=rsh)
                self._blocks_vs = self._zeros(scale_shape, jnp.float32,
                                              sharding=rsh)

        # host-side slot state (fixed width, mirrors the device arrays)
        self._lengths = np.zeros((cfg.n_slots,), np.int32)
        self._last_tok = np.zeros((cfg.n_slots,), np.int32)
        self._temps = np.zeros((cfg.n_slots,), np.float32)
        self._scratch: Dict[int, Any] = {}    # rid -> (sk, sv)
        self._draft_scratch: Dict[int, Any] = {}    # rid -> (dk, dv)

        self.decode_compile_count = 0
        self.prefill_compile_count = 0
        # spec decode accounting (greedy rows only: sampled rows always
        # force accept = 0 and would just dilute the rate)
        self.spec_verify_compile_count = 0
        self.draft_prefill_compile_count = 0
        self.spec_tokens_proposed = 0
        self.spec_tokens_accepted = 0
        self.steps = 0
        self.tokens_generated = 0
        # disagg hand-off accounting (serve/disagg.py)
        self.kv_exports = 0
        self.kv_imports = 0
        self.remote_prefix_tokens = 0
        self.on_step: Optional[Callable[[Dict], None]] = None
        # flight-recorder root for engine-owned work that belongs to no
        # single request (multi-request decode batches)
        self._trace_id = events.new_trace_id()
        # step attribution: decode FLOPs are computed analytically
        # (re-lowering the decode program for cost_analysis would trip
        # the compile-once invariant the tests assert on)
        self.profiler = None
        if cfg.step_profile:
            from ray_tpu.util import profiling
            leaves = jax.tree_util.tree_leaves(params)
            self._n_params = int(sum(x.size for x in leaves))
            self._param_bytes = float(sum(
                x.size * getattr(x.dtype, "itemsize", 4) for x in leaves))
            self._kv_elt_bytes = float(jnp.dtype(dtype).itemsize)
            self.profiler = profiling.StepProfiler(
                "decode_step", emit_span=False)
        self._build_fns()

    # ------------------------------------------------------------ device fns
    def _zeros(self, shape, dtype, sharding=None):
        import jax.numpy as jnp
        with self._mesh_ctx():
            if self.mesh is not None:
                # allocate THROUGH a jitted zeros with explicit output
                # sharding: under a multi-process mesh this yields a
                # global array directly (device_put of a host value
                # cannot), and on one process it is equivalent. The
                # TARGET slot pool shards batch/kv_heads; callers pass
                # their own sharding for anything whose divisibility was
                # pruned against a different shape; everything else is
                # replicated.
                from jax.sharding import NamedSharding, PartitionSpec
                sh = sharding
                if sh is None:
                    if self._pool_sharding is not None \
                            and tuple(shape) == self._target_pool_shape:
                        sh = self._pool_sharding
                    else:
                        sh = NamedSharding(self.mesh, PartitionSpec())
                return _sharded_zeros(sh)(tuple(shape), jnp.dtype(dtype))
            return jnp.zeros(shape, dtype)

    def _mesh_ctx(self):
        if self.mesh is None:
            import contextlib
            return contextlib.nullcontext()
        from ray_tpu.parallel.mesh import use_mesh
        return use_mesh(self.mesh)

    def _build_fns(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.sampling import sample_logits_dynamic
        cfg = self.config
        model = self.model
        top_k, top_p = cfg.top_k, cfg.top_p
        # donation rebinds the pool buffers in place on TPU; CPU (tests)
        # doesn't implement donation and would warn every call
        donate = jax.default_backend() != "cpu"

        def prefill(params, sk, sv, tokens, pos0, n_real, rng, temp):
            # one budgeted chunk of prompt through the cached path;
            # samples the would-be next token (used only on the last
            # chunk, where it is the request's first generated token)
            self.prefill_compile_count += 1    # traces once: fixed shapes
            cache = {"k": sk, "v": sv, "idx": pos0}
            logits, new = model.apply({"params": params}, tokens,
                                      cache=cache, chunked_prefill=True)
            last = jax.lax.dynamic_index_in_dim(logits, n_real - 1,
                                                axis=1, keepdims=False)
            tok = sample_logits_dynamic(last, rng, temp[None],
                                        top_k=top_k, top_p=top_p)
            return tok[0].astype(jnp.int32), new["k"], new["v"]

        def insert(pk, pv, sk, sv, slot):
            # scratch carries prefill_chunk of padding tail; the slot
            # takes the first max_len entries
            sk = sk[:, :, :cfg.max_len]
            sv = sv[:, :, :cfg.max_len]
            pk = jax.lax.dynamic_update_slice(pk, sk, (0, slot, 0, 0, 0))
            pv = jax.lax.dynamic_update_slice(pv, sv, (0, slot, 0, 0, 0))
            return pk, pv

        def decode(params, pk, pv, lengths, toks, rng, temps):
            # ONE program for the life of the engine: fixed [n_slots]
            # shapes, per-slot idx vector. Python side effect below runs
            # only at trace time — it counts XLA cache misses. The key
            # splits INSIDE the program (returned for the next step) so
            # the host does exactly one dispatch per decoded token.
            self.decode_compile_count += 1
            rng, sub = jax.random.split(rng)
            cache = {"k": pk, "v": pv, "idx": lengths}
            logits, new = model.apply({"params": params}, toks[:, None],
                                      cache=cache)
            tok = sample_logits_dynamic(logits[:, -1, :], sub, temps,
                                        top_k=top_k, top_p=top_p)
            return tok.astype(jnp.int32), new["k"], new["v"], rng

        self._prefill_fn = jax.jit(
            prefill, donate_argnums=(1, 2) if donate else ())
        self._insert_fn = jax.jit(
            insert, donate_argnums=(0, 1) if donate else ())
        self._decode_fn = jax.jit(
            decode, donate_argnums=(1, 2) if donate else ())

        self._spec_step_fn = None
        self._draft_prefill_fn = None
        if self._spec is not None:
            from ray_tpu.inference.spec_decode import build_spec_step
            draft_model = self._draft_model

            def _count_verify_trace():
                # the fused draft+verify program REPLACES decode as the
                # per-step program: both counters watch the same
                # compile-once contract (tests assert 1 and 1)
                self.decode_compile_count += 1
                self.spec_verify_compile_count += 1

            self._spec_step_fn = jax.jit(
                build_spec_step(model, draft_model, self._spec.k,
                                top_k, top_p,
                                on_trace=_count_verify_trace),
                donate_argnums=(2, 3, 4, 5) if donate else ())

            def draft_prefill(dparams, sk, sv, tokens, pos0):
                # prompt KV for the draft cache: same chunked path as
                # the target's prefill, no sampling (the draft never
                # emits during prefill)
                self.draft_prefill_compile_count += 1
                cache = {"k": sk, "v": sv, "idx": pos0}
                _, new = draft_model.apply({"params": dparams}, tokens,
                                           cache=cache,
                                           chunked_prefill=True)
                return new["k"], new["v"]

            self._draft_prefill_fn = jax.jit(
                draft_prefill, donate_argnums=(1, 2) if donate else ())

        if self.prefix_cache is not None and self._kv_quant:
            self._build_quant_span_fns(donate)
        elif self.prefix_cache is not None:
            mcfg = self.model.cfg
            span = (mcfg.n_layers, 1, cfg.prefill_chunk,
                    mcfg.n_kv_heads, mcfg.head_dim)

            def save_span(bk, bv, sk, sv, slot, dst, src):
                # one completed prefill chunk: scratch[src:src+C] ->
                # block storage (slot row, dst offset). Fixed span
                # shape + traced scalar offsets = one compile, ever.
                ck = jax.lax.dynamic_slice(sk, (0, 0, src, 0, 0), span)
                cv = jax.lax.dynamic_slice(sv, (0, 0, src, 0, 0), span)
                bk = jax.lax.dynamic_update_slice(bk, ck,
                                                  (0, slot, dst, 0, 0))
                bv = jax.lax.dynamic_update_slice(bv, cv,
                                                  (0, slot, dst, 0, 0))
                return bk, bv

            def load_span(sk, sv, bk, bv, slot, src, dst):
                # hit path: cached block -> this request's scratch; the
                # suffix prefill then attends over it exactly as if the
                # chunk had just been computed (bit-identical values).
                ck = jax.lax.dynamic_slice(bk, (0, slot, src, 0, 0), span)
                cv = jax.lax.dynamic_slice(bv, (0, slot, src, 0, 0), span)
                sk = jax.lax.dynamic_update_slice(sk, ck, (0, 0, dst, 0, 0))
                sv = jax.lax.dynamic_update_slice(sv, cv, (0, 0, dst, 0, 0))
                return sk, sv

            def export_span(bk, bv, slot, src):
                # disagg hand-off, sender half: one cached block out of
                # the pool (device value; the caller materializes it to
                # host for the wire). Fixed span shape + traced offsets
                # = one compile, ever — same contract as load/save.
                ck = jax.lax.dynamic_slice(bk, (0, slot, src, 0, 0), span)
                cv = jax.lax.dynamic_slice(bv, (0, slot, src, 0, 0), span)
                return ck, cv

            def import_span(bk, bv, ck, cv, slot, dst):
                # disagg hand-off, receiver half: a span computed on
                # ANOTHER replica lands in this engine's block pool; the
                # normal load_span hit path then serves it exactly like
                # a locally prefilled block.
                bk = jax.lax.dynamic_update_slice(bk, ck,
                                                  (0, slot, dst, 0, 0))
                bv = jax.lax.dynamic_update_slice(bv, cv,
                                                  (0, slot, dst, 0, 0))
                return bk, bv

            self._save_span_fn = jax.jit(
                save_span, donate_argnums=(0, 1) if donate else ())
            self._load_span_fn = jax.jit(
                load_span, donate_argnums=(0, 1) if donate else ())
            self._export_span_fn = jax.jit(export_span)
            self._import_span_fn = jax.jit(
                import_span, donate_argnums=(0, 1) if donate else ())

    def _build_quant_span_fns(self, donate):
        """int8 variants of the four span programs: same fixed span
        shape + traced offsets (= one compile each, ever), but the block
        side carries int8 values plus fp32 per-(position, head) scale
        rows and the scratch side stays full precision — quantize on
        save, dequantize on load, ship compressed on export."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.inference.kv_quant import dequantize_kv, quantize_kv
        cfg = self.config
        mcfg = self.model.cfg
        span = (mcfg.n_layers, 1, cfg.prefill_chunk,
                mcfg.n_kv_heads, mcfg.head_dim)
        sspan = span[:-1]
        cdtype = self._cache_dtype

        def save_spanq(bk, bv, bks, bvs, sk, sv, slot, dst, src):
            ck = jax.lax.dynamic_slice(sk, (0, 0, src, 0, 0), span)
            cv = jax.lax.dynamic_slice(sv, (0, 0, src, 0, 0), span)
            qk, ks = quantize_kv(ck)
            qv, vs = quantize_kv(cv)
            bk = jax.lax.dynamic_update_slice(bk, qk, (0, slot, dst, 0, 0))
            bv = jax.lax.dynamic_update_slice(bv, qv, (0, slot, dst, 0, 0))
            bks = jax.lax.dynamic_update_slice(bks, ks, (0, slot, dst, 0))
            bvs = jax.lax.dynamic_update_slice(bvs, vs, (0, slot, dst, 0))
            return bk, bv, bks, bvs

        def load_spanq(sk, sv, bk, bv, bks, bvs, slot, src, dst):
            qk = jax.lax.dynamic_slice(bk, (0, slot, src, 0, 0), span)
            qv = jax.lax.dynamic_slice(bv, (0, slot, src, 0, 0), span)
            ks = jax.lax.dynamic_slice(bks, (0, slot, src, 0), sspan)
            vs = jax.lax.dynamic_slice(bvs, (0, slot, src, 0), sspan)
            sk = jax.lax.dynamic_update_slice(
                sk, dequantize_kv(qk, ks, cdtype), (0, 0, dst, 0, 0))
            sv = jax.lax.dynamic_update_slice(
                sv, dequantize_kv(qv, vs, cdtype), (0, 0, dst, 0, 0))
            return sk, sv

        def export_spanq(bk, bv, bks, bvs, slot, src):
            qk = jax.lax.dynamic_slice(bk, (0, slot, src, 0, 0), span)
            qv = jax.lax.dynamic_slice(bv, (0, slot, src, 0, 0), span)
            ks = jax.lax.dynamic_slice(bks, (0, slot, src, 0), sspan)
            vs = jax.lax.dynamic_slice(bvs, (0, slot, src, 0), sspan)
            return qk, qv, ks, vs

        def import_spanq(bk, bv, bks, bvs, qk, qv, ks, vs, slot, dst):
            bk = jax.lax.dynamic_update_slice(bk, qk, (0, slot, dst, 0, 0))
            bv = jax.lax.dynamic_update_slice(bv, qv, (0, slot, dst, 0, 0))
            bks = jax.lax.dynamic_update_slice(bks, ks, (0, slot, dst, 0))
            bvs = jax.lax.dynamic_update_slice(bvs, vs, (0, slot, dst, 0))
            return bk, bv, bks, bvs

        self._save_span_fn = jax.jit(
            save_spanq, donate_argnums=(0, 1, 2, 3) if donate else ())
        self._load_span_fn = jax.jit(
            load_spanq, donate_argnums=(0, 1) if donate else ())
        self._export_span_fn = jax.jit(export_spanq)
        self._import_span_fn = jax.jit(
            import_spanq, donate_argnums=(0, 1, 2, 3) if donate else ())

    # -------------------------------------------------------------- intake
    def submit(self, tokens, max_new_tokens: int = 64,
               temperature: Optional[float] = None,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               hold: bool = False) -> RequestHandle:
        """Queue one prompt; returns a streaming RequestHandle.
        deadline_s is relative (seconds from now) — a request still
        queued past it fails with finish_reason='deadline'.
        hold=True parks the request in the queue (FIFO position kept)
        until release_hold() — the remote-prefill hand-off window."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if len(tokens) == 0:
            raise ValueError("empty prompt")
        if len(tokens) > self.config.max_len - 1:
            raise ValueError(
                f"prompt ({len(tokens)} tokens) must leave room to "
                f"decode in a {self.config.max_len}-token slot")
        req = Request(tokens=tokens, max_new_tokens=int(max_new_tokens),
                      temperature=temperature, eos_id=eos_id,
                      deadline_s=(time.monotonic() + deadline_s
                                  if deadline_s is not None else None),
                      trace_ctx=events.current_context())
        with self._work:
            if self._stop:
                raise RuntimeError("engine is stopped")
            h = self.sched.submit(req, hold=hold)
            self._work.notify_all()
        return h

    def release_hold(self, handle: RequestHandle):
        """End a hold-submitted request's hand-off window: it becomes
        admissible on the next step (its imported prefix — if the
        hand-off landed — now matches via the radix trie exactly like a
        locally cached one). Safe to call on any failure path."""
        with self._work:
            self.sched.release_hold(handle.rid)
            self._work.notify_all()

    def begin_drain(self):
        """Preemption drain: refuse new submissions (submit raises and
        the serving layer re-routes), finish everything in flight. The
        loop keeps stepping until the last slot evicts."""
        with self._work:
            self.sched.begin_drain()
            self._work.notify_all()

    # --------------------------------------------------------------- loop
    def start(self) -> "InferenceEngine":
        with self._lock:
            if self._thread is None:
                self._stop = False
                self._thread = threading.Thread(
                    target=self._loop, name="inference-engine", daemon=True)
                self._thread.start()
        return self

    def stop(self):
        with self._work:
            self._stop = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        with self._lock:
            self.sched.fail_all(RuntimeError("engine stopped"))

    def _loop(self):
        while True:
            with self._work:
                if self._stop:
                    return
                if not self.sched.has_work():
                    # deadline sweeps still need an occasional wake
                    self._work.wait(timeout=0.05)
                    if self._stop:
                        return
            try:
                self.step()
            except Exception as e:           # engine must not die silently
                with self._lock:
                    self.sched.fail_all(e)

    # --------------------------------------------------------------- step
    def step(self) -> bool:
        """One engine iteration: reap cancels/deadlines, run budgeted
        prefill chunks (admission), advance every occupied slot one
        token. Returns True if any device work ran."""
        import jax

        with self._lock:
            t_iter0 = time.perf_counter()
            now = time.monotonic()
            for st in self.sched.reap(now):
                self._scratch.pop(st.rid, None)
                self._draft_scratch.pop(st.rid, None)
            chunks = self.sched.plan_prefill()
            did = False
            for ch in chunks:
                self._run_prefill_chunk(ch, now)
                did = True
            t_admit = time.perf_counter()

            # capacity eviction BEFORE the step: a full slot has nowhere
            # to write its next token
            for st in self.sched.active_states():
                if self._lengths[st.slot] >= self.config.max_len:
                    self.sched.evict(st, FINISH_LENGTH)
            active = self.sched.active_states()
            if active:
                # decode is a BATCH phase: when one request occupies the
                # engine its span adopts that request's trace (the
                # acceptance path — one Serve call renders its decode
                # windows inline); with several co-resident traces the
                # span records under the engine's own root trace with
                # slot attribution instead of picking a favorite
                traces = {st.span.trace_id for st in active
                          if st.span is not None}
                if len(active) == 1 and active[0].span is not None:
                    d_trace = active[0].span.trace_id
                    d_parent = active[0].span.span_id
                elif len(traces) == 1:
                    d_trace, d_parent = next(iter(traces)), None
                else:
                    d_trace, d_parent = self._trace_id, None
                dspan = events.start_span(
                    "engine.decode", category="engine",
                    trace_id=d_trace, parent_span_id=d_parent,
                    step=self.steps, slots_active=len(active),
                    slots_occupied=self.sched.occupancy(),
                    queue_depth=self.sched.queue_depth())
                compiles0 = self.decode_compile_count
                t_dec0 = time.perf_counter()
                if self._spec is not None:
                    with self._mesh_ctx():
                        (out, acc, self._pool_k, self._pool_v,
                         self._dpool_k, self._dpool_v, self._rng) = \
                            self._spec_step_fn(
                                self.params, self._draft_params,
                                self._pool_k, self._pool_v,
                                self._dpool_k, self._dpool_v,
                                self._lengths, self._last_tok,
                                self._rng, self._temps)
                    out_host = np.asarray(out)
                    acc_host = np.asarray(acc)
                else:
                    with self._mesh_ctx():
                        toks, self._pool_k, self._pool_v, self._rng = \
                            self._decode_fn(
                                self.params, self._pool_k, self._pool_v,
                                self._lengths, self._last_tok, self._rng,
                                self._temps)
                    toks_host = np.asarray(toks)
                t_dec1 = time.perf_counter()
                # capture before decode_emit: an evicted state's slot is
                # None by the time the profiler reads it
                slots = [st.slot for st in active]
                now = time.monotonic()
                n_emitted = 0
                if self._spec is not None:
                    # accepted prefix + one bonus token per slot. ALL
                    # accept-count control flow happens HERE, on
                    # materialized numpy values — a Python branch on the
                    # traced count inside the program is the classic
                    # retrace bug (rtlint RT002 fixture).
                    for st in active:
                        slot = st.slot
                        accepted = int(acc_host[slot])
                        if self._temps[slot] <= 0.0:
                            self.spec_tokens_proposed += self._spec_k
                            self.spec_tokens_accepted += accepted
                        for j in range(accepted + 1):
                            self._lengths[slot] += 1
                            tok = int(out_host[slot, j])
                            self._last_tok[slot] = tok
                            self.tokens_generated += 1
                            n_emitted += 1
                            self.sched.decode_emit(st, tok, now)
                            if st.slot is None:
                                break    # finished (EOS / max tokens)
                else:
                    for st in active:
                        slot = st.slot
                        self._lengths[slot] += 1
                        self._last_tok[slot] = toks_host[slot]
                        self.tokens_generated += 1
                        n_emitted += 1
                        self.sched.decode_emit(st, int(toks_host[slot]),
                                               now)
                if self.decode_compile_count > compiles0:
                    # a decode retrace is THE perf cliff this engine is
                    # built to avoid — make every occurrence a first-class
                    # timeline event (tests assert the count stays at 1)
                    events.record_instant(
                        "engine.compile", category="engine",
                        trace_id=d_trace, parent_span_id=dspan.span_id,
                        fn="decode", compile_count=self.decode_compile_count)
                attribution = {}
                if self.profiler is not None:
                    attribution = self._profile_decode(
                        [int(self._lengths[s]) for s in slots],
                        t_iter0, t_admit, t_dec0, t_dec1)
                dspan.end(tokens=n_emitted, **attribution)
                did = True
            self.steps += 1
            if self.on_step is not None:
                try:
                    self.on_step(self.stats())
                except Exception:
                    pass
            return did

    def _profile_decode(self, kv_lens, t_iter0, t_admit, t_dec0, t_dec1):
        """Per-step attribution: decode compute vs prefill/admission work
        ("data wait" — tokens can't advance while it runs) vs host gap
        (scheduler bookkeeping + idle between steps). Returns the attrs
        attached to the engine.decode span (mfu + phase ms) so the
        timeline answers the stuck-MFU question inline."""
        from ray_tpu.util import profiling
        mcfg = self.model.cfg
        flops = profiling.decode_step_flops(
            self._n_params, mcfg.n_layers, mcfg.n_heads, mcfg.head_dim,
            kv_lens)
        nbytes = profiling.decode_step_bytes(
            self._param_bytes, mcfg.n_layers, mcfg.n_kv_heads,
            mcfg.head_dim, kv_lens, self._kv_elt_bytes)
        rec = self.profiler.observe(
            compute_s=t_dec1 - t_dec0, data_s=t_admit - t_iter0,
            begin_t=t_iter0, end_t=t_dec1, tokens=len(kv_lens),
            flops=flops, bytes_accessed=nbytes)
        return {k: rec[k] for k in ("mfu", "mfu_compute", "compute_ms",
                                    "host_gap_ms", "data_wait_ms",
                                    "roofline_bound") if k in rec}

    def _run_prefill_chunk(self, ch: PrefillChunk, now: float):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        st = ch.state
        if st.span is None:
            # first chunk == admission: open the engine-slot span. It
            # parents under the submitting request's propagated context
            # (Serve path) or roots its own trace (direct engine use),
            # and carries the queue-wait the built-in scheduler-latency
            # metric is derived from.
            ctx = st.request.trace_ctx
            st.span = events.start_span(
                "engine.slot", category="engine",
                trace_id=ctx[0] if ctx else None,
                parent_span_id=ctx[1] if ctx else None,
                rid=st.rid, slot=st.slot,
                prompt_tokens=len(st.request.tokens),
                queue_wait_ms=round(
                    (now - st.handle.submitted_t) * 1e3, 3))
        sk_sv = self._scratch.get(st.rid)
        if sk_sv is None:
            sk_sv = (self._zeros(self._scratch_shape, self._cache_dtype),
                     self._zeros(self._scratch_shape, self._cache_dtype))
            if st.prefix_nodes:
                # radix hit: the matched span's KV comes out of the
                # block pool as device-side copies — no forward pass
                # runs over [0, prefix_matched)
                sk_sv = self._restore_prefix(st, *sk_sv)
        sk, sv = sk_sv
        dk_dv = None
        if self._spec is not None:
            dk_dv = self._draft_scratch.get(st.rid)
            if dk_dv is None:
                dk_dv = (self._zeros(self._draft_scratch_shape,
                                     self._cache_dtype),
                         self._zeros(self._draft_scratch_shape,
                                     self._cache_dtype))
                if st.prefix_matched:
                    # the block pool holds TARGET KV only; the (cheap)
                    # draft re-prefills the matched range so its cache
                    # stays aligned with the target's
                    dk_dv = self._draft_replay(st, *dk_dv)
        prompt = st.request.tokens
        chunk = np.zeros((1, cfg.prefill_chunk), np.int32)
        chunk[0, :ch.length] = prompt[ch.start:ch.start + ch.length]
        self._rng, k = jax.random.split(self._rng)
        pspan = events.start_span(
            "engine.prefill", category="engine",
            trace_id=st.span.trace_id, parent_span_id=st.span.span_id,
            rid=st.rid, slot=st.slot, offset=ch.start, length=ch.length,
            is_last=ch.is_last,
            slots_occupied=self.sched.occupancy())
        compiles0 = self.prefill_compile_count
        with self._mesh_ctx():
            tok, sk, sv = self._prefill_fn(
                self.params, sk, sv, jnp.asarray(chunk),
                np.int32(ch.start), np.int32(ch.length), k,
                np.float32(st.temperature))
        if self.prefill_compile_count > compiles0:
            events.record_instant(
                "engine.compile", category="engine",
                trace_id=st.span.trace_id,
                parent_span_id=pspan.span_id, fn="prefill",
                compile_count=self.prefill_compile_count)
        pspan.end()
        if self._spec is not None:
            with self._mesh_ctx():
                ndk, ndv = self._draft_prefill_fn(
                    self._draft_params, dk_dv[0], dk_dv[1],
                    jnp.asarray(chunk), np.int32(ch.start))
            dk_dv = (ndk, ndv)
        if ch.is_last:
            slot = st.slot
            if self.prefix_cache is not None:
                self._populate_prefix(st, sk, sv)
            with self._mesh_ctx():
                self._pool_k, self._pool_v = self._insert_fn(
                    self._pool_k, self._pool_v, sk, sv, np.int32(slot))
                if self._spec is not None:
                    self._dpool_k, self._dpool_v = self._insert_fn(
                        self._dpool_k, self._dpool_v, dk_dv[0], dk_dv[1],
                        np.int32(slot))
            self._scratch.pop(st.rid, None)
            self._draft_scratch.pop(st.rid, None)
            self._lengths[slot] = len(prompt)
            first = int(tok)
            self._last_tok[slot] = first
            self._temps[slot] = st.temperature
            self.sched.prefill_done(st, first, time.monotonic())
        else:
            if self._kv_quant and self.prefix_cache is not None:
                sk, sv = self._publish_chunk_quant(st, sk, sv, ch)
            self._scratch[st.rid] = (sk, sv)
            if self._spec is not None:
                self._draft_scratch[st.rid] = dk_dv
            self.sched.advance_prefill(st, ch.length)

    # ------------------------------------------------------- prefix cache
    def _restore_prefix(self, st, sk, sv):
        """Copy the matched trie blocks into this request's scratch
        cache ([0, prefix_matched) chunk by chunk), then unpin them.
        Runs once, on the request's first prefill chunk, under the
        engine lock — eviction cannot race the copies."""
        C = self.config.prefill_chunk
        with self._mesh_ctx():
            for i, node in enumerate(st.prefix_nodes):
                bslot, boff = divmod(node.block, self._blocks_per_slot)
                if self._kv_quant:
                    sk, sv = self._load_span_fn(
                        sk, sv, self._blocks_k, self._blocks_v,
                        self._blocks_ks, self._blocks_vs,
                        np.int32(bslot), np.int32(boff * C),
                        np.int32(i * C))
                else:
                    sk, sv = self._load_span_fn(
                        sk, sv, self._blocks_k, self._blocks_v,
                        np.int32(bslot), np.int32(boff * C),
                        np.int32(i * C))
        events.record_instant(
            "engine.prefix_hit", category="engine",
            trace_id=st.span.trace_id if st.span else None,
            parent_span_id=st.span.span_id if st.span else None,
            rid=st.rid, slot=st.slot, matched_tokens=st.prefix_matched,
            prompt_tokens=len(st.request.tokens))
        self.sched.unpin_prefix(st)
        return sk, sv

    def _populate_prefix(self, st, sk, sv):
        """Miss path, at prefill completion: extend the trie over every
        full chunk of the prompt and fill the newly allocated blocks
        from scratch (already-present chunks are skipped — their KV is
        identical by construction)."""
        C = self.config.prefill_chunk
        created = self.prefix_cache.insert(st.request.tokens)
        if not created:
            return
        with self._mesh_ctx():
            for off, block in created:
                bslot, boff = divmod(block, self._blocks_per_slot)
                self._save_block(sk, sv, bslot, boff * C, off)

    def _save_block(self, sk, sv, bslot, dst, src):
        """One chunk scratch -> block pool, quantizing when int8 is on
        (caller holds the lock and the mesh context)."""
        if self._kv_quant:
            (self._blocks_k, self._blocks_v, self._blocks_ks,
             self._blocks_vs) = self._save_span_fn(
                self._blocks_k, self._blocks_v, self._blocks_ks,
                self._blocks_vs, sk, sv,
                np.int32(bslot), np.int32(dst), np.int32(src))
        else:
            self._blocks_k, self._blocks_v = self._save_span_fn(
                self._blocks_k, self._blocks_v, sk, sv,
                np.int32(bslot), np.int32(dst), np.int32(src))

    def _publish_chunk_quant(self, st, sk, sv, ch):
        """int8 miss path, non-final chunks: publish each COMPLETED full
        chunk into the quantized block pool as it finishes, then reload
        the dequantized values into this request's OWN scratch — the
        miss request attends exactly the numbers a later prefix-cache
        hit will restore, so greedy output is bit-identical hit vs miss
        (write-through caching, compile-once edition). The final chunk
        (full or padded) is save-only in _populate_prefix: the admission
        match is capped one token short of the prompt, so no hit ever
        restores it and both paths attend it raw."""
        C = self.config.prefill_chunk
        end = ch.start + ch.length
        created = self.prefix_cache.insert(st.request.tokens[:end])
        with self._mesh_ctx():
            for off, block in created:
                bslot, boff = divmod(block, self._blocks_per_slot)
                self._save_block(sk, sv, bslot, boff * C, off)
                sk, sv = self._load_span_fn(
                    sk, sv, self._blocks_k, self._blocks_v,
                    self._blocks_ks, self._blocks_vs,
                    np.int32(bslot), np.int32(boff * C), np.int32(off))
        return sk, sv

    def _draft_replay(self, st, dk, dv):
        """Prefix-hit draft warmup: re-prefill the matched range through
        the draft model (chunk-aligned by construction; prefix_matched
        is a multiple of prefill_chunk)."""
        import jax.numpy as jnp
        C = self.config.prefill_chunk
        prompt = st.request.tokens
        with self._mesh_ctx():
            for off in range(0, st.prefix_matched, C):
                chunk = np.zeros((1, C), np.int32)
                chunk[0, :] = prompt[off:off + C]
                dk, dv = self._draft_prefill_fn(
                    self._draft_params, dk, dv, jnp.asarray(chunk),
                    np.int32(off))
        return dk, dv

    # --------------------------------------------------- disagg hand-off
    def export_kv_blocks(self, tokens, max_chunks: Optional[int] = None):
        """Sender half of the prefill/decode hand-off: copy the cached
        KV blocks covering ``tokens``' chunk-aligned prefix out of the
        block pool as host arrays. Returns ``(covered_tokens, spans)``
        where ``spans`` is ``[(k, v), ...]`` of fixed span shape
        ``[n_layers, 1, prefill_chunk, Hkv, D]`` — the unit
        serve/disagg.py frames onto the data plane. Defaults to the
        admission cap (one token short of the prompt) so the importing
        engine's match covers exactly what its scheduler would use.
        Blocks stay pinned for the duration of the copy; compile-once
        holds (one fixed-shape export program)."""
        if self.prefix_cache is None:
            return 0, []
        C = self.config.prefill_chunk
        cap = (max(0, len(tokens) - 1) // C if max_chunks is None
               else max(0, int(max_chunks)))
        with self._lock:
            nodes = self.prefix_cache.walk(tokens, cap)
            spans = []
            try:
                with self._mesh_ctx():
                    for node in nodes:
                        bslot, boff = divmod(node.block,
                                             self._blocks_per_slot)
                        if self._kv_quant:
                            # int8 wire: values + scale rows — the
                            # hand-off payload shrinks with the pool
                            qk, qv, ks, vs = self._export_span_fn(
                                self._blocks_k, self._blocks_v,
                                self._blocks_ks, self._blocks_vs,
                                np.int32(bslot), np.int32(boff * C))
                            spans.append(
                                (np.asarray(qk), np.asarray(qv),
                                 np.asarray(ks), np.asarray(vs)))
                        else:
                            ck, cv = self._export_span_fn(
                                self._blocks_k, self._blocks_v,
                                np.int32(bslot), np.int32(boff * C))
                            spans.append((np.asarray(ck), np.asarray(cv)))
            finally:
                self.prefix_cache.release(nodes)
            if spans:
                self.kv_exports += 1
        return len(spans) * C, spans

    def import_kv_blocks(self, tokens, spans) -> int:
        """Receiver half: land remotely prefilled spans in this engine's
        block pool and extend the trie over them, so the NEXT admission
        of ``tokens`` (or any prompt sharing the prefix) hits via the
        ordinary load_span path — no forward pass runs over the imported
        range, and greedy output is bit-identical to a local prefill
        (the blocks are the same deterministic computation, just done
        elsewhere). Chunks already cached locally are skipped; returns
        the number of prompt tokens newly covered."""
        if self.prefix_cache is None or not spans:
            return 0
        import jax.numpy as jnp
        C = self.config.prefill_chunk
        n = min(len(spans), len(tokens) // C)
        if n <= 0:
            return 0
        from ray_tpu.inference import kv_quant as kvq
        with self._lock:
            created = self.prefix_cache.insert(
                [int(t) for t in tokens[:n * C]])
            with self._mesh_ctx():
                for off, block in created:
                    span = spans[off // C]
                    bslot, boff = divmod(block, self._blocks_per_slot)
                    if self._kv_quant:
                        if len(span) == 4:
                            qk, qv, ks, vs = span
                        else:
                            # fp wire from a non-quantized exporter:
                            # quantize host-side (bit-identical math to
                            # the device save path)
                            qk, ks = kvq.quantize_kv_np(span[0])
                            qv, vs = kvq.quantize_kv_np(span[1])
                        (self._blocks_k, self._blocks_v, self._blocks_ks,
                         self._blocks_vs) = self._import_span_fn(
                            self._blocks_k, self._blocks_v,
                            self._blocks_ks, self._blocks_vs,
                            jnp.asarray(qk, jnp.int8),
                            jnp.asarray(qv, jnp.int8),
                            jnp.asarray(ks, jnp.float32),
                            jnp.asarray(vs, jnp.float32),
                            np.int32(bslot), np.int32(boff * C))
                    else:
                        if len(span) == 4:
                            # int8 wire into an fp pool: dequantize on
                            # the host before landing the block
                            ck = kvq.dequantize_kv_np(span[0], span[2])
                            cv = kvq.dequantize_kv_np(span[1], span[3])
                        else:
                            ck, cv = span
                        self._blocks_k, self._blocks_v = \
                            self._import_span_fn(
                                self._blocks_k, self._blocks_v,
                                jnp.asarray(ck, self._cache_dtype),
                                jnp.asarray(cv, self._cache_dtype),
                                np.int32(bslot), np.int32(boff * C))
            imported = len(created) * C
            if imported:
                self.kv_imports += 1
                self.remote_prefix_tokens += imported
        return imported

    # -------------------------------------------------------------- stats
    def stats(self) -> Dict:
        out = {
            "n_slots": self.config.n_slots,
            "slots_occupied": self.sched.occupancy(),
            "slots_free": self.config.n_slots - self.sched.occupancy(),
            "queue_depth": self.sched.queue_depth(),
            "active": len(self.sched.active_slots()),
            "steps": self.steps,
            "tokens_generated": self.tokens_generated,
            "decode_compile_count": self.decode_compile_count,
            "draining": self.sched.draining,
        }
        if self.prefix_cache is not None:
            out.update(self.prefix_cache.stats())
            out["kv_exports"] = self.kv_exports
            out["kv_imports"] = self.kv_imports
            out["remote_prefix_tokens"] = self.remote_prefix_tokens
        if self._spec is not None:
            prop = self.spec_tokens_proposed
            out["spec_k"] = self._spec_k
            out["spec_verify_compile_count"] = \
                self.spec_verify_compile_count
            out["spec_tokens_proposed"] = prop
            out["spec_tokens_accepted"] = self.spec_tokens_accepted
            out["spec_accept_rate"] = (
                round(self.spec_tokens_accepted / prop, 4) if prop
                else 0.0)
        if self._kv_quant:
            from ray_tpu.inference import kv_quant as kvq
            mcfg = self.model.cfg
            out["kv_quant"] = "int8"
            out["kv_quant_slot_gain"] = round(
                kvq.slot_gain(mcfg.head_dim, self._fp_itemsize), 3)
            out["kv_quant_slot_gain_vs_fp16"] = round(
                kvq.slot_gain(mcfg.head_dim, 2), 3)
        return out
