"""`LLMDeployment`: the continuous-batching engine behind a Serve
deployment, streaming tokens over the existing replica/handle streaming
path (replica.handle_stream -> ObjectRefGenerator).

Usage::

    from ray_tpu import serve
    from ray_tpu.inference import LLMDeployment

    app = serve.deployment(LLMDeployment).bind("llama-debug", n_slots=4)
    serve.run(app, name="llm")
    h = serve.get_app_handle("llm").options(stream=True)
    for tok in h.remote([1, 2, 3], max_new_tokens=32):
        ...

Each streamed request holds one engine slot; a client that drops the
iterator mid-generation cancels the request in a ``finally`` — the slot
is reclaimed by the next engine step and the queue metrics decrement
(see tests/test_serve_streaming.py). Composes with Serve multiplexing
(the deployment is an ordinary callable; sticky model-id routing works
unchanged) and, for models wider than one host, with sharded replicas —
pass a mesh + pre-sharded params via ``params_fn``.

Metrics (ray_tpu/util/metrics.py, aggregated at /metrics):
  serve_llm_ttft_ms        histogram  time to first token per request
  serve_llm_tpot_ms        histogram  per-token latency after the first
  serve_llm_requests_total counter    finished requests, by finish_reason
  serve_llm_tokens_total   counter    generated tokens
  serve_llm_slot_occupancy gauge      occupied slots (per engine step)
  serve_llm_queue_depth    gauge      queued (unadmitted) requests
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.inference.engine import EngineConfig, InferenceEngine


def _resolve_model(model):
    """Accept a registry name, a TransformerConfig, or a ready
    TransformerLM module."""
    from ray_tpu.models import MODEL_REGISTRY, TransformerLM
    from ray_tpu.models.transformer import TransformerConfig
    if isinstance(model, str):
        return TransformerLM(MODEL_REGISTRY[model])
    if isinstance(model, TransformerConfig):
        return TransformerLM(model)
    return model


class LLMDeployment:
    """Serve callable hosting one InferenceEngine.

    model: registry name / TransformerConfig / TransformerLM.
    params_fn: optional zero-arg callable returning the param tree
        (checkpoint restore, sharded init, ...); defaults to random
        init with `seed` — the CI/bench shape.
    Engine knobs (n_slots, max_len, prefill_chunk, prefill_budget,
    eos_id, temperature, top_k, top_p) mirror EngineConfig.

    Streaming resume (``__serve_resumable__``): a stream severed by
    replica death is resubmitted by the handle layer with
    ``resume_tokens=<tokens already delivered>``; the generated-so-far
    suffix rides the prompt through the chunked-prefill path on the
    survivor and generation continues from the exact next position —
    zero dropped, zero duplicated tokens for greedy decoding (sampled
    decoding resumes from the same position but re-draws randomness).
    """

    # handle.py resubmits severed streams with resume_tokens= instead of
    # restarting them from scratch (serve/handle.py stream re-route)
    __serve_resumable__ = True
    # streams yield COALESCED chunks (lists of token ids) instead of one
    # token per frame: the handle layer unpacks them back to per-token
    # iteration while the wire carries ~stream_coalesce_tokens per
    # round-trip (serve/handle.py DeploymentResponseGenerator)
    __serve_coalesce_stream__ = True

    def __init__(self, model="llama-debug", *, n_slots: int = 4,
                 max_len: int = 256, prefill_chunk: int = 32,
                 prefill_budget: int = 64, eos_id: int = -1,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, params_fn=None, mesh=None,
                 seed: int = 0, prefix_cache_slots: int = 2,
                 stream_coalesce_tokens: int = 8,
                 stream_coalesce_ms: float = 20.0,
                 weights_key: Optional[str] = "auto",
                 spec_decode=None, kv_quant: str = "none"):
        import jax

        self.model = _resolve_model(model)
        # coalescing knobs: how many decoded tokens ride one streaming
        # frame (handle->router->replica->proxy round-trip) and how long
        # a partial batch may wait before flushing. The FIRST token of
        # every request is always flushed eagerly — TTFT never pays the
        # coalesce window.
        self.stream_coalesce_tokens = max(1, int(stream_coalesce_tokens))
        self.stream_coalesce_ms = max(0.0, float(stream_coalesce_ms))
        if params_fn is not None:
            # weight-plane attach (serve/weights.py): the first replica
            # to run params_fn publishes the tree via broadcast_weights
            # (plain-put fallback) and records the ref; later attaches —
            # fleet shell revivals included — get a zero-copy local
            # arena read instead of re-running the loader. weights_key
            # "auto" derives a key from (model, seed) for registry-name
            # models; pass an explicit key for config/module models or
            # None to always re-run params_fn.
            if weights_key == "auto":
                weights_key = (f"llm/{model}/{seed}"
                               if isinstance(model, str) else None)
            from ray_tpu.serve.weights import resolve_weight_source
            params = resolve_weight_source(weights_key, params_fn)
        else:
            import jax.numpy as jnp
            tokens0 = jnp.zeros((1, min(8, max_len)), jnp.int32)
            params = self.model.init(jax.random.PRNGKey(seed),
                                     tokens0)["params"]
        cfg = EngineConfig(n_slots=n_slots, max_len=max_len,
                           prefill_chunk=prefill_chunk,
                           prefill_budget=prefill_budget, eos_id=eos_id,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, kv_quant=kv_quant,
                           prefix_cache_slots=max(0, int(prefix_cache_slots)))
        # spec_decode: None | SpecDecodeConfig | kwargs dict — draft-model
        # speculative decoding (inference/spec_decode.py); greedy output
        # is bit-identical to non-speculative serving, only throughput
        # moves. kv_quant="int8" halves+ the prefix-block HBM footprint.
        self.engine = InferenceEngine(self.model, params, cfg, mesh=mesh,
                                      seed=seed, spec=spec_decode)
        self._metrics = _EngineMetrics()
        self.engine.on_step = self._metrics.on_step
        self.engine.start()

    # ------------------------------------------------------------- serving
    def __call__(self, prompt_tokens, max_new_tokens: int = 64,
                 temperature: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 resume_tokens=None,
                 stream_coalesce_tokens: Optional[int] = None,
                 stream_coalesce_ms: Optional[float] = None):
        """Streaming generator: yields COALESCED chunks — lists of token
        ids, up to ``stream_coalesce_tokens`` long, flushed at least
        every ``stream_coalesce_ms`` — so one handle/replica/proxy
        round-trip carries a batch instead of a single token. The first
        token of the stream is always its own eager chunk (TTFT is
        unaffected). Invoked with .options(stream=True) this rides the
        replica streaming path and the handle layer unpacks chunks back
        to per-token iteration (``__serve_coalesce_stream__``); the
        finally-cancel frees the slot when the client drops the iterator
        mid-generation (GeneratorExit lands here).

        resume_tokens: tokens a previous attempt already delivered —
        they re-prefill as part of the prompt (the chunked-prefill path
        makes this one budgeted admission, not a decode replay) and only
        the continuation is yielded."""
        from ray_tpu._private import events
        coalesce_n = (self.stream_coalesce_tokens
                      if stream_coalesce_tokens is None
                      else max(1, int(stream_coalesce_tokens)))
        flush_s = (self.stream_coalesce_ms
                   if stream_coalesce_ms is None
                   else max(0.0, float(stream_coalesce_ms))) / 1e3
        if resume_tokens:
            resume_tokens = [int(t) for t in resume_tokens]
            prompt_tokens = list(prompt_tokens) + resume_tokens
            max_new_tokens = int(max_new_tokens) - len(resume_tokens)
            if max_new_tokens <= 0:
                return   # the dead replica already delivered everything
        # the request span chains under the replica task's propagated
        # trace context (the generator body runs inside handle_stream's
        # execution, which re-establishes it per resumption), and the
        # engine parents this request's slot span under it via the
        # trace_context around submit()
        req_span = events.start_span(
            "engine.request", category="serve",
            prompt_tokens=len(prompt_tokens),
            max_new_tokens=int(max_new_tokens))
        handle = self._submit_request(prompt_tokens, max_new_tokens,
                                      temperature, eos_id, deadline_s,
                                      req_span)
        prev_t: Optional[float] = None
        n_tokens = 0
        try:
            while True:
                try:
                    if prev_t is None:
                        # eager first chunk: exactly one token, flushed
                        # the moment the engine emits it
                        batch = [handle.next()]
                    else:
                        batch = handle.next_many(coalesce_n, flush_s)
                except StopIteration:
                    break
                now = time.monotonic()
                if prev_t is None:
                    ttft = now - handle.submitted_t
                    self._metrics.first_token(ttft)
                    events.record_instant(
                        "engine.first_token", category="serve",
                        trace_id=req_span.trace_id,
                        parent_span_id=req_span.span_id,
                        ttft_ms=round(ttft * 1e3, 3))
                else:
                    # inter-token latency inside a coalesced chunk is
                    # the per-token share of the batch gap
                    self._metrics.next_token(
                        (now - prev_t) / len(batch), n=len(batch))
                prev_t = now
                n_tokens += len(batch)
                self._metrics.flushed()
                yield batch
        finally:
            # client walked away OR stream completed; cancel is a no-op
            # on a finished request
            handle.cancel()
            reason = handle.finish_reason or "cancelled"
            self._metrics.finished(reason)
            self._metrics.prefix(self.engine.prefix_cache)
            req_span.end(finish_reason=reason, tokens=n_tokens)

    def _submit_request(self, prompt_tokens, max_new_tokens, temperature,
                        eos_id, deadline_s, req_span):
        """Admission hook: submit one request to the engine under the
        request span's trace context. The disaggregated decode tier
        (serve/disagg.py) overrides this to run the KV hand-off —
        hold-submit, import remotely prefilled blocks, release — before
        admission plans any prefill."""
        from ray_tpu._private import events
        with events.trace_context(req_span.trace_id, req_span.span_id):
            return self.engine.submit(prompt_tokens,
                                      max_new_tokens=max_new_tokens,
                                      temperature=temperature,
                                      eos_id=eos_id,
                                      deadline_s=deadline_s)

    def generate(self, prompt_tokens, **kw):
        """Non-streaming convenience: returns the full token list
        (coalesced chunks flattened)."""
        return [t for chunk in self.__call__(prompt_tokens, **kw)
                for t in chunk]

    # ------------------------------------------------------------- control
    def stats(self) -> Dict:
        return self.engine.stats()

    def begin_drain(self):
        """Preemption notice (serve/replica.py relays it here): the
        engine refuses new submissions — the handle layer re-routes
        them — while queued and in-flight requests run to completion."""
        self.engine.begin_drain()

    def on_shell_attach(self):
        """Fleet cold-start hook (serve/fleet.py ReplicaShell.attach):
        runs INSIDE a pre-warmed shell after construction, BEFORE the
        replica is published to routing tables. One tiny greedy
        generate forces every fixed-shape XLA program to compile here,
        so the requests held through the cold start never pay compile
        latency — serve_cold_start_ms measures weights + compile, TTFT
        afterwards looks warm. Best-effort: a warmup failure still
        lets the replica serve (the first request compiles instead)."""
        try:
            for _ in self.__call__([1], max_new_tokens=1):
                pass
        except Exception:
            import logging
            logging.getLogger(__name__).warning(
                "shell-attach warmup failed; first request will compile",
                exc_info=True)

    def drain_status(self) -> Dict:
        st = self.engine.stats()
        return {"draining": st["draining"],
                "pending": st["slots_occupied"] + st["queue_depth"]}

    def check_health(self):
        if self.engine._thread is not None \
                and not self.engine._thread.is_alive():
            raise RuntimeError("inference engine loop died")

    def reconfigure(self, user_config):
        # prefill budget is the one knob safe to move live (it is read
        # per step); everything else is baked into compiled shapes
        if isinstance(user_config, dict) and "prefill_budget" in user_config:
            self.engine.sched.prefill_budget = max(
                1, int(user_config["prefill_budget"]))

    def __del__(self):
        try:
            self.engine.stop()
        except Exception:
            pass


class _EngineMetrics:
    """TTFT/TPOT/occupancy/queue-depth wiring (util/metrics.py)."""

    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge, Histogram
        ms = [1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
              2500.0, 5000.0]
        self.ttft = Histogram("serve_llm_ttft_ms",
                              "time to first token (ms)", boundaries=ms)
        self.tpot = Histogram("serve_llm_tpot_ms",
                              "inter-token latency (ms)", boundaries=ms)
        self.requests = Counter("serve_llm_requests_total",
                                "finished requests",
                                tag_keys=("finish_reason",))
        self.tokens = Counter("serve_llm_tokens_total", "generated tokens")
        self.occupancy = Gauge("serve_llm_slot_occupancy",
                               "occupied KV slots")
        self.queue_depth = Gauge("serve_llm_queue_depth",
                                 "queued (unadmitted) requests")
        self.hit_rate = Gauge("prefix_hit_rate",
                              "radix-cache hit rate over request lookups")
        self.tokens_saved = Gauge("prefix_tokens_saved",
                                  "prompt tokens whose prefill the "
                                  "radix cache skipped (cumulative)")
        self.flush_rate = Gauge("stream_flushes_per_s",
                                "coalesced stream chunks flushed per "
                                "second (1s sliding window)")
        self.flushes = Counter("serve_llm_stream_flushes_total",
                               "coalesced stream chunks flushed")
        self._lock = threading.Lock()
        self._flush_window: list = []      # monotonic stamps, last ~1s

    def on_step(self, stats: Dict):
        self.occupancy.set(stats["slots_occupied"])
        self.queue_depth.set(stats["queue_depth"])

    def first_token(self, dt_s: float):
        self.ttft.observe(dt_s * 1000.0)
        self.tokens.inc()

    def next_token(self, dt_s: float, n: int = 1):
        self.tpot.observe(dt_s * 1000.0)
        self.tokens.inc(n)

    def flushed(self):
        self.flushes.inc()
        now = time.monotonic()
        with self._lock:
            self._flush_window.append(now)
            cut = now - 1.0
            while self._flush_window and self._flush_window[0] < cut:
                self._flush_window.pop(0)
            self.flush_rate.set(float(len(self._flush_window)))

    def prefix(self, cache):
        if cache is not None:
            self.hit_rate.set(cache.hit_rate)
            self.tokens_saved.set(float(cache.tokens_saved))

    def finished(self, reason: str):
        self.requests.inc(tags={"finish_reason": reason})
