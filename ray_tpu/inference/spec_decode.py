"""Draft-model speculative decoding for the slot-pool engine — exactly
one extra fixed-shape program (ROADMAP item 1).

The classic transform: a small draft model proposes K greedy tokens per
slot, the target model scores all K+1 positions in ONE batched forward
(the same chunked-prefill cached-attention path prefill uses), and the
engine emits the longest agreeing prefix plus one bonus token from the
target's own distribution. Greedy decoding is EXACT by construction —
every emitted token is an argmax of target logits computed over the
identical cache contents the one-token decode program would have seen,
so spec-on and spec-off streams are bit-identical and the accept rate
only moves throughput, never output.

Compile-once discipline (the engine's whole perf story):

- the draft loop is a ``lax.scan`` of K+1 single-token draft steps
  INSIDE the program (scan iteration i also writes draft KV for its
  input token at position ``len+i``, so the draft cache is complete
  however many drafts the target accepts);
- the verify forward is one fixed ``[n_slots, K+1]`` call — shapes
  never depend on accept counts;
- accept counts come back to the host as an ``[n_slots]`` vector and
  ALL control flow on them (how many tokens to emit) happens host-side
  on materialized numpy values — a Python branch on the traced accept
  count inside the program is the classic retrace bug (rtlint RT002
  has a fixture for it);
- both KV pools are K positions longer than ``max_len`` so the fixed
  write window ``[len, len+K+1)`` never clamps back onto live entries
  (the same padding argument as the engine's prefill scratch).

Rejected speculation leaves garbage KV above ``len + accepted + 1`` in
both pools; it is never attended (the causal mask cuts at the per-row
``idx``) and the next step's window overwrites it before it could be.

Sampled rows (temperature > 0) fall back to emitting one
target-sampled token per step: position 0 of the verify output is
drawn through ``sample_logits_dynamic`` exactly like the non-spec
decode program, and the accept count is forced to 0, so sampling
semantics (one fresh draw per emitted token) are preserved.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional


@dataclasses.dataclass
class SpecDecodeConfig:
    """Speculative-decoding knobs for :class:`InferenceEngine`.

    draft_model: registry name / TransformerConfig / TransformerLM of
        the (small) proposer. Must share the target's vocab.
    k: draft tokens proposed per step; the engine emits 1..k+1 tokens
        per decode step depending on agreement.
    draft_params_fn: optional zero-arg callable returning the draft
        param tree (checkpoint restore, or the target's own params for
        a self-draft upper-bound probe); defaults to random init with
        ``draft_seed``.
    """
    draft_model: Any = None
    k: int = 4
    draft_params_fn: Optional[Callable[[], Any]] = None
    draft_seed: int = 0


def resolve_spec(spec) -> Optional[SpecDecodeConfig]:
    """Accept None / SpecDecodeConfig / kwargs dict."""
    if spec is None:
        return None
    if isinstance(spec, SpecDecodeConfig):
        cfg = spec
    elif isinstance(spec, dict):
        cfg = SpecDecodeConfig(**spec)
    else:
        raise TypeError(f"spec_decode: expected SpecDecodeConfig or "
                        f"dict, got {type(spec).__name__}")
    if cfg.draft_model is None:
        raise ValueError("spec_decode requires a draft_model")
    if cfg.k < 1:
        raise ValueError(f"spec_decode k={cfg.k}; must be >= 1")
    return cfg


def resolve_draft(cfg: SpecDecodeConfig, target_cfg):
    """Build (draft_module, draft_params) and validate compatibility
    with the target model config."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import MODEL_REGISTRY, TransformerLM
    from ray_tpu.models.transformer import TransformerConfig
    m = cfg.draft_model
    if isinstance(m, str):
        m = TransformerLM(MODEL_REGISTRY[m])
    elif isinstance(m, TransformerConfig):
        m = TransformerLM(m)
    if m.cfg.vocab_size != target_cfg.vocab_size:
        raise ValueError(
            f"draft vocab {m.cfg.vocab_size} != target vocab "
            f"{target_cfg.vocab_size}: accept comparison is meaningless")
    if cfg.draft_params_fn is not None:
        params = cfg.draft_params_fn()
    else:
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = m.init(jax.random.PRNGKey(cfg.draft_seed),
                        tokens0)["params"]
    return m, params


def accept_prefix(drafts, out, temps):
    """Longest agreeing prefix, per slot (pure jnp; shape-stable).

    drafts: int32[S, K] — the draft's proposals d_1..d_K.
    out:    int32[S, K+1] — the target's choice at every position
            (out[:, j] is what the target emits AFTER j accepted
            drafts; out[:, :K] is what d_{j+1} must equal to count).
    temps:  fp32[S] — sampled rows (temp > 0) force accept = 0.

    Returns int32[S] in [0, K].
    """
    import jax.numpy as jnp
    match = (drafts == out[:, :-1]).astype(jnp.int32)
    acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
    return jnp.where(jnp.asarray(temps) > 0.0, 0, acc).astype(jnp.int32)


def build_spec_step(model, draft_model, k: int, top_k: int, top_p: float,
                    on_trace: Optional[Callable[[], None]] = None):
    """The fused draft+verify step function (un-jitted; the engine jits
    it with pool donation and owns the compile counter via
    ``on_trace``).

    Signature of the returned function::

        spec_step(params, dparams, pk, pv, dk, dv, lengths, toks,
                  rng, temps)
          -> (out [S, K+1], accept [S], pk, pv, dk, dv, rng)

    where pk/pv are the target slot pools, dk/dv the draft slot pools
    (both ``max_len + K`` positions long), lengths/toks/temps the
    engine's host-mirrored per-slot vectors. ``out[s, :accept[s]+1]``
    are the tokens slot ``s`` emits this step.
    """
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.sampling import sample_logits_dynamic

    def spec_step(params, dparams, pk, pv, dk, dv, lengths, toks, rng,
                  temps):
        if on_trace is not None:
            on_trace()       # trace-time only: counts XLA cache misses
        rng, sub = jax.random.split(rng)

        # ---- draft: K+1 greedy single-token steps under lax.scan.
        # Iteration j consumes cur_j (cur_0 = the last emitted token),
        # writes its KV at position len+j, and proposes cur_{j+1}; the
        # extra (K+1)th iteration exists only for its KV write, so a
        # fully accepted step leaves the draft cache complete through
        # position len+K.
        def draft_body(carry, j):
            dk, dv, cur = carry
            cache = {"k": dk, "v": dv, "idx": lengths + j}
            logits, new = draft_model.apply({"params": dparams},
                                            cur[:, None], cache=cache)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (new["k"], new["v"], nxt), nxt

        (dk, dv, _), ys = jax.lax.scan(draft_body, (dk, dv, toks),
                                       jnp.arange(k + 1))
        drafts = jnp.transpose(ys[:k])                     # [S, K]

        # ---- verify: ONE target forward over [last_tok, d_1..d_K].
        # chunked_prefill reuses the cached-attention path (per-row idx,
        # causal window) — the same program shape prefill compiles.
        seq = jnp.concatenate([toks[:, None], drafts], axis=1)
        logits, new = model.apply({"params": params}, seq,
                                  cache={"k": pk, "v": pv,
                                         "idx": lengths},
                                  chunked_prefill=True)
        # position 0 samples exactly like the non-spec decode program
        # (greedy rows reduce to argmax; sampled rows get a fresh draw)
        out0 = sample_logits_dynamic(logits[:, 0, :], sub, temps,
                                     top_k=top_k, top_p=top_p)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        out = jnp.concatenate(
            [out0[:, None].astype(jnp.int32), greedy[:, 1:]], axis=1)
        accept = accept_prefix(drafts, out, temps)
        return out, accept, new["k"], new["v"], dk, dv, rng

    return spec_step
