"""Request scheduling for the continuous-batching engine.

The scheduler owns everything about a request EXCEPT the tensors: the
FIFO admission queue, the per-step prefill-token budget (prefill must
never stall in-flight decodes, so each engine iteration spends at most
``prefill_budget`` prompt tokens), cancellation, and per-request
deadlines. The engine (engine.py) asks it three questions per step —
what to evict, what to prefill, what is active — and reports back what
happened; all device-side state (KV pool, scratch caches) stays in the
engine.

Thread model: the engine serializes all scheduler calls under its own
lock; request handles (the streaming consumer side) only touch their
thread-safe token queue and the `cancelled` flag.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from typing import Any, Dict, List, Optional

FINISH_EOS = "eos"
FINISH_LENGTH = "length"
FINISH_CANCELLED = "cancelled"
FINISH_DEADLINE = "deadline"

_SENTINEL = object()


@dataclasses.dataclass
class Request:
    """One generation request. `tokens` is the prompt (1-D int32);
    per-request sampling knobs default to the engine's config."""
    tokens: Any
    max_new_tokens: int = 64
    temperature: Optional[float] = None
    eos_id: Optional[int] = None
    # absolute monotonic deadline for STARTING (admission); a queued
    # request past it fails with FINISH_DEADLINE instead of occupying a
    # slot it can no longer use
    deadline_s: Optional[float] = None
    # (trace_id, span_id) captured at submit: the flight recorder
    # parents this request's engine-slot span under the submitting
    # task/request span, so a Serve call renders proxy -> replica ->
    # engine-slot as one trace
    trace_ctx: Optional[Any] = None


class RequestHandle:
    """Streaming consumer side of a submitted request: iterate to
    receive token ids as the engine emits them; ``cancel()`` frees the
    slot (or dequeues) at the next engine step. Dropping the iterator
    mid-stream and calling cancel() are equivalent."""

    def __init__(self, rid: int):
        self.rid = rid
        self.cancelled = False
        self.finish_reason: Optional[str] = None
        self.submitted_t = time.monotonic()
        self.first_token_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.error: Optional[BaseException] = None
        # prompt tokens whose prefill the radix cache skipped (set at
        # admission; 0 = miss or cache disabled). Serving probes split
        # TTFT hit-vs-miss on this.
        self.prefix_matched = 0
        self._q: "queue.Queue" = queue.Queue()
        self._drained = False
        self._end_seen = False     # sentinel met inside next_many()

    # ------------------------------------------------------ engine side
    def _emit(self, token: int, now: float):
        if self.first_token_t is None:
            self.first_token_t = now
        self._q.put(int(token))

    def _finish(self, reason: str, now: float,
                error: Optional[BaseException] = None):
        self.finish_reason = reason
        self.finished_t = now
        self.error = error
        self._q.put(_SENTINEL)

    # ---------------------------------------------------- consumer side
    def cancel(self):
        self.cancelled = True

    def __iter__(self):
        return self

    def __next__(self) -> int:
        return self.next()

    def next(self, timeout: Optional[float] = None) -> int:
        """Blocking next with an explicit timeout (raises queue.Empty).
        Safe to call past exhaustion: keeps raising StopIteration
        instead of blocking on an empty queue."""
        if self._drained or self._end_seen:
            self._drained = True
            if self.error is not None:
                raise self.error
            raise StopIteration
        item = self._q.get(timeout=timeout)
        if item is _SENTINEL:
            self._drained = True
            if self.error is not None:
                raise self.error
            raise StopIteration
        return item

    def next_many(self, max_tokens: int, flush_s: float = 0.0,
                  timeout: Optional[float] = None) -> List[int]:
        """Coalesced drain: block for ONE token (so the first token of a
        batch is never delayed), then keep collecting already-emitted
        tokens until ``max_tokens`` are gathered or ``flush_s`` elapses.
        Returns a non-empty list; end-of-stream raises StopIteration on
        the call AFTER the one that returned the final tokens — no token
        is ever held back behind the flush timer once the engine
        finished the request."""
        first = self.next(timeout=timeout)   # raises at end of stream
        out = [first]
        deadline = time.monotonic() + max(0.0, flush_s)
        while len(out) < max_tokens:
            remaining = deadline - time.monotonic()
            try:
                if remaining > 0:
                    item = self._q.get(timeout=remaining)
                else:
                    item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is _SENTINEL:
                # finish mid-batch: deliver what we have NOW; the next
                # call surfaces StopIteration (or the error)
                self._end_seen = True
                break
            out.append(item)
        return out

    def tokens(self) -> List[int]:
        """Drain to completion and return every generated token."""
        return list(self)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submitted_t


@dataclasses.dataclass
class RequestState:
    """Scheduler-internal record. Lifecycle:
    QUEUED -> PREFILLING -> ACTIVE -> (finished)."""
    rid: int
    request: Request
    handle: RequestHandle
    temperature: float
    eos_id: int
    status: str = "QUEUED"
    slot: Optional[int] = None
    prefill_pos: int = 0          # prompt tokens already prefilled
    generated: int = 0
    last_token: int = 0
    span: Optional[Any] = None    # flight-recorder engine.slot span
    # radix-cache admission state: matched prefix length (its prefill
    # is skipped — the engine copies the blocks instead) and the pinned
    # trie nodes backing it (released once the copy lands in scratch)
    prefix_matched: int = 0
    prefix_nodes: Optional[List[Any]] = None
    # remote-prefill admission state (serve/disagg.py): a held request
    # keeps its FIFO queue position but is skipped by plan_prefill until
    # release_hold — the window in which its KV blocks are in flight
    # from another replica. Cancellation/deadline reaping still applies.
    hold: bool = False


@dataclasses.dataclass
class PrefillChunk:
    """One budgeted piece of prompt to run this step."""
    state: RequestState
    start: int                    # offset into the prompt
    length: int                   # real tokens in this chunk
    is_last: bool


class Scheduler:
    """FIFO admission with a per-step prefill-token budget.

    A request occupies a slot from the moment its first chunk runs
    (chunked prefill writes straight into a scratch cache that is
    inserted into the slot when the prompt completes), so admission =
    free slot AND budget. Multiple requests may be mid-prefill in one
    step if the budget covers them.
    """

    def __init__(self, n_slots: int, prefill_budget: int,
                 default_temperature: float = 0.0, eos_id: int = -1,
                 chunk_size: Optional[int] = None, prefix_cache=None):
        self.n_slots = n_slots
        # optional RadixPrefixCache (prefix_cache.py): consulted once
        # per request at admission; matched spans skip prefill entirely
        self.prefix_cache = prefix_cache
        self.prefill_budget = max(1, int(prefill_budget))
        # static shape of one prefill call; a planned chunk never
        # exceeds it (the engine pads shorter chunks up to it)
        self.chunk_size = int(chunk_size or self.prefill_budget)
        self.default_temperature = default_temperature
        self.default_eos = eos_id
        self._rid = itertools.count()
        self._queue: List[RequestState] = []      # FIFO, QUEUED only
        self._prefilling: List[RequestState] = []  # slot held, prompt wip
        self._active: Dict[int, RequestState] = {}  # slot -> state
        self._free_slots = list(range(n_slots))
        # drain mode (preemption notice): new submissions are refused —
        # the caller re-routes them to a surviving replica — while
        # everything already queued/prefilling/active runs to completion
        self.draining = False

    # ----------------------------------------------------------- draining
    def begin_drain(self):
        """Flip admission off ahead of a preemption kill. Idempotent;
        there is no un-drain — a drained replica is on its way out."""
        self.draining = True

    def drained(self) -> bool:
        """True once every in-flight request has finished (the point at
        which the controller may reap the replica early). Held requests
        still count as pending — their hand-off will release them."""
        return self.draining and not (self._queue or self._prefilling
                                      or self._active)

    # ------------------------------------------------------------ intake
    def submit(self, request: Request, hold: bool = False) -> RequestHandle:
        """hold=True enqueues WITHOUT making the request admissible: it
        keeps its FIFO position while a KV hand-off is in flight and
        becomes plannable on release_hold() (or on any failure path the
        caller takes — a hold that is never released is only reaped by
        cancel/deadline)."""
        if self.draining:
            raise RuntimeError(
                "scheduler is draining (preemption notice): new "
                "requests must be routed to another replica")
        rid = next(self._rid)
        handle = RequestHandle(rid)
        temp = (request.temperature
                if request.temperature is not None
                else self.default_temperature)
        eos = (request.eos_id if request.eos_id is not None
               else self.default_eos)
        st = RequestState(rid=rid, request=request, handle=handle,
                          temperature=float(temp), eos_id=int(eos),
                          hold=bool(hold))
        self._queue.append(st)
        return handle

    def release_hold(self, rid: int) -> bool:
        """Make a held request admissible (its hand-off landed — or
        failed, in which case admission falls back to local prefill).
        Idempotent; False when the request already left the queue."""
        for st in self._queue:
            if st.rid == rid:
                st.hold = False
                return True
        return False

    # -------------------------------------------------------- accounting
    def queue_depth(self) -> int:
        return len(self._queue)

    def occupancy(self) -> int:
        return self.n_slots - len(self._free_slots)

    def active_states(self) -> List[RequestState]:
        return list(self._active.values())

    def active_slots(self) -> List[int]:
        return list(self._active.keys())

    # ------------------------------------------------------------- sweep
    def reap(self, now: Optional[float] = None) -> List[RequestState]:
        """Remove cancelled/expired requests from every stage; returns
        the reaped states (slots already released). Called at the top of
        each engine step so a dropped client frees its slot within one
        iteration."""
        now = time.monotonic() if now is None else now
        reaped: List[RequestState] = []

        keep = []
        for st in self._queue:
            if st.handle.cancelled:
                st.status = "FINISHED"
                st.handle._finish(FINISH_CANCELLED, now)
                reaped.append(st)
            elif (st.request.deadline_s is not None
                    and now > st.request.deadline_s):
                st.status = "FINISHED"
                st.handle._finish(FINISH_DEADLINE, now)
                reaped.append(st)
            else:
                keep.append(st)
        self._queue = keep

        keep = []
        for st in self._prefilling:
            if st.handle.cancelled:
                self._release(st, FINISH_CANCELLED, now)
                reaped.append(st)
            else:
                keep.append(st)
        self._prefilling = keep

        for slot, st in list(self._active.items()):
            if st.handle.cancelled:
                self._release(st, FINISH_CANCELLED, now)
                reaped.append(st)
        return reaped

    def _release(self, st: RequestState, reason: str, now: float,
                 error: Optional[BaseException] = None):
        st.status = "FINISHED"
        self.unpin_prefix(st)
        freed_slot = st.slot
        if st.slot is not None:
            self._active.pop(st.slot, None)
            self._free_slots.append(st.slot)
            self._free_slots.sort()
            st.slot = None
        st.handle._finish(reason, now, error)
        if st.span is not None:
            # the engine-slot span covers admission -> eviction; the
            # finish reason and token count ride as attributes, and an
            # eviction instant marks the exact slot-release point
            from ray_tpu._private import events
            events.record_instant(
                "engine.evict", category="engine",
                trace_id=st.span.trace_id,
                parent_span_id=st.span.span_id,
                slot=freed_slot, reason=reason)
            st.span.end(finish_reason=reason,
                        tokens_generated=st.generated)
            st.span = None

    # --------------------------------------------------------- admission
    def plan_prefill(self) -> List[PrefillChunk]:
        """Spend this step's prefill budget: continue mid-prefill
        requests first (their slot is already held), then admit queued
        requests into free slots, FIFO. Chunks never exceed the
        remaining budget, so one long prompt spreads across steps and
        never stalls in-flight decodes for more than `prefill_budget`
        tokens of work."""
        budget = self.prefill_budget
        chunks: List[PrefillChunk] = []
        for st in list(self._prefilling):
            if budget <= 0:
                break
            budget -= self._plan_one(st, budget, chunks)
        qi = 0
        while budget > 0 and qi < len(self._queue) and self._free_slots:
            if self._queue[qi].hold:
                # remote-prefill hand-off in flight: the request keeps
                # its FIFO position but later arrivals may admit past it
                qi += 1
                continue
            st = self._queue.pop(qi)
            st.slot = self._free_slots.pop(0)
            st.status = "PREFILLING"
            if self.prefix_cache is not None:
                matched, nodes = self.prefix_cache.match(st.request.tokens)
                if matched:
                    # the matched span's prefill is SKIPPED: the engine
                    # copies the pinned blocks into scratch before the
                    # first planned chunk runs; planning starts at the
                    # first uncached token
                    st.prefill_pos = matched
                    st.prefix_matched = matched
                    st.prefix_nodes = nodes
                    st.handle.prefix_matched = matched
            self._prefilling.append(st)
            budget -= self._plan_one(st, budget, chunks)
        return chunks

    def unpin_prefix(self, st: RequestState):
        """Matched blocks have been copied into the request's scratch:
        the trie nodes may be evicted again. Idempotent; also called on
        release so a cancelled mid-admission request never wedges a pin."""
        if st.prefix_nodes and self.prefix_cache is not None:
            self.prefix_cache.release(st.prefix_nodes)
        st.prefix_nodes = None

    def _plan_one(self, st: RequestState, budget: int,
                  chunks: List[PrefillChunk]) -> int:
        """Plan budgeted fixed-shape chunks for one request; the planned
        start offsets account for chunks earlier in THIS step's list."""
        prompt_len = len(st.request.tokens)
        pos = st.prefill_pos
        spent = 0
        while budget - spent > 0 and pos < prompt_len:
            n = min(budget - spent, self.chunk_size, prompt_len - pos)
            chunks.append(PrefillChunk(state=st, start=pos, length=n,
                                       is_last=pos + n >= prompt_len))
            pos += n
            spent += n
        return spent

    def prefill_done(self, st: RequestState, first_token: int,
                     now: float):
        """The prompt is fully in the slot and the first token sampled:
        the request joins the decode batch (or finishes immediately if
        the first token already terminates it)."""
        self._prefilling.remove(st)
        st.status = "ACTIVE"
        st.prefill_pos = len(st.request.tokens)
        st.last_token = int(first_token)
        st.generated = 1
        st.handle._emit(first_token, now)
        if self._is_finished(st, first_token):
            self._release(st, self._finish_reason(st, first_token), now)
        else:
            self._active[st.slot] = st

    def advance_prefill(self, st: RequestState, n: int):
        st.prefill_pos += n

    # ------------------------------------------------------------ decode
    def decode_emit(self, st: RequestState, token: int, now: float):
        """One decoded token for an active slot: emit, then evict on
        EOS/max-tokens (slot returns to the free list immediately)."""
        st.last_token = int(token)
        st.generated += 1
        st.handle._emit(token, now)
        if self._is_finished(st, token):
            self._release(st, self._finish_reason(st, token), now)

    def _is_finished(self, st: RequestState, token: int) -> bool:
        if st.eos_id >= 0 and int(token) == st.eos_id:
            return True
        return st.generated >= st.request.max_new_tokens

    def _finish_reason(self, st: RequestState, token: int) -> str:
        if st.eos_id >= 0 and int(token) == st.eos_id:
            return FINISH_EOS
        return FINISH_LENGTH

    def evict(self, st: RequestState, reason: str,
              error: Optional[BaseException] = None):
        """Force-evict (engine-detected condition, e.g. slot capacity
        reached before max_new_tokens)."""
        self._release(st, reason, time.monotonic(), error)

    def fail_all(self, error: BaseException):
        """Engine shutdown/crash: fail everything still in flight."""
        now = time.monotonic()
        for st in (list(self._queue) + list(self._prefilling)
                   + list(self._active.values())):
            self._release(st, FINISH_CANCELLED, now, error)
        self._queue.clear()
        self._prefilling.clear()

    def has_work(self) -> bool:
        """Actionable work only: a queue holding nothing but held
        requests doesn't spin the engine loop — release_hold notifies
        the loop's condition when a hand-off lands."""
        return bool(self._prefilling or self._active
                    or any(not st.hold for st in self._queue))
