"""Radix/prefix KV cache bookkeeping for the continuous-batching engine.

Shared prompts (system prompts, few-shot preambles, resumed streams)
re-run prefill from scratch on every request even though the KV they
produce is identical. This module keeps a **token trie over completed
prefills**: each node covers exactly one ``prefill_chunk`` of tokens and
references an immutable KV *block* — a ``prefill_chunk``-aligned span
inside the engine's fixed-shape cache-slot arrays (the engine owns the
device memory; this module owns only the addressing, ref-counts and LRU
state, so it is pure host Python and unit-testable without JAX).

On admission the scheduler asks for the longest chunk-aligned prefix
already in the trie; the engine then *copies* the matched blocks into
the request's scratch cache instead of running prefill over them —
admission cost for the matched span drops from a forward pass to a
device-side memcpy. Misses populate the trie when their prefill
completes. The match is capped one token short of the full prompt so
the final chunk always prefills (that pass samples the request's first
token — the sampling path never changes, which is what keeps greedy
output bit-identical hit vs miss).

Blocks are ref-counted: a node matched by an in-flight request stays
pinned until its span has been copied into that request's scratch, so
LRU eviction under block pressure can never reuse memory a request is
about to read. Eviction is leaf-only (an interior node's children are
unreachable without it) and strictly LRU over unpinned leaves.

Thread model: every call happens under the engine's step lock (the
scheduler and engine already serialize there); nothing here locks.
"""

from __future__ import annotations

import hashlib
import itertools
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple


def _roll_fp(parent_fp: int, key: Sequence[int]) -> int:
    """Rolling 64-bit path fingerprint: hash of (parent fingerprint,
    this chunk's tokens). Two prompts share fingerprint ``i`` iff they
    share their first ``(i+1) * chunk_size`` tokens (modulo hash
    collision), so a flat fingerprint SET is enough to answer "how deep
    does this replica's trie cover my prompt" without shipping tokens."""
    h = hashlib.blake2b(digest_size=8)
    h.update(parent_fp.to_bytes(8, "little"))
    h.update(struct.pack(f"<{len(key)}i", *(int(t) for t in key)))
    return int.from_bytes(h.digest(), "little")


def chunk_fingerprints(tokens: Sequence[int], chunk_size: int,
                       max_chunks: Optional[int] = None) -> List[int]:
    """Path fingerprints of a prompt's full chunks: element ``i`` covers
    ``tokens[: (i+1) * chunk_size]``. The router computes these for an
    incoming prompt and intersects them with each replica's published
    summary to find the deepest cluster-wide match (serve/disagg.py)."""
    C = int(chunk_size)
    if C <= 0:
        raise ValueError("chunk_size must be positive")
    n = len(tokens) // C
    if max_chunks is not None:
        n = min(n, max(0, int(max_chunks)))
    fps: List[int] = []
    fp = 0
    for c in range(n):
        fp = _roll_fp(fp, tokens[c * C:(c + 1) * C])
        fps.append(fp)
    return fps


class TrieNode:
    """One ``chunk_size``-token edge of the radix trie. ``block`` is the
    engine-assigned block id whose KV span holds this chunk's keys and
    values; ``pins`` counts in-flight requests that matched through this
    node and have not yet copied it out."""

    __slots__ = ("key", "block", "children", "parent", "pins", "stamp",
                 "fp")

    def __init__(self, key: Optional[Tuple[int, ...]], block: Optional[int],
                 parent: Optional["TrieNode"]):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "TrieNode"] = {}
        self.pins = 0
        self.stamp = 0
        # path fingerprint (root -> this node); the unit the cluster-wide
        # routing summary is built from
        self.fp = 0

    def __repr__(self):
        return (f"TrieNode(block={self.block}, pins={self.pins}, "
                f"children={len(self.children)})")


class RadixPrefixCache:
    """Host-side trie + block-pool accounting.

    chunk_size: tokens per trie node / per block (the engine's
        ``prefill_chunk`` — spans stay chunk-aligned so the fixed-shape
        compile-once programs cover every copy).
    n_blocks: total KV blocks the engine carved out of its cache-slot
        arrays (``prefix_cache_slots * (max_len // prefill_chunk)``).
    """

    def __init__(self, chunk_size: int, n_blocks: int):
        if chunk_size <= 0:
            raise ValueError("chunk_size must be positive")
        self.chunk_size = int(chunk_size)
        self.n_blocks = int(n_blocks)
        self._free: List[int] = list(range(self.n_blocks))
        self._root = TrieNode(None, None, None)
        self._clock = itertools.count(1)
        # stats (exposed in engine.stats(); fed to the serve gauges)
        self.lookups = 0
        self.hits = 0
        self.tokens_saved = 0
        self.evictions = 0
        self.blocks_cached = 0

    # ------------------------------------------------------------- lookup
    def match(self, tokens: Sequence[int]) -> Tuple[int, List[TrieNode]]:
        """Longest chunk-aligned prefix of ``tokens`` present in the
        trie, capped at ``len(tokens) - 1`` so at least one token always
        runs prefill (the pass that samples the first generated token).
        Matched nodes come back PINNED — the caller must ``release()``
        them once their spans have been copied out."""
        C = self.chunk_size
        limit = max(0, (len(tokens) - 1)) // C
        node = self._root
        matched: List[TrieNode] = []
        for c in range(limit):
            child = node.children.get(
                tuple(int(t) for t in tokens[c * C:(c + 1) * C]))
            if child is None:
                break
            matched.append(child)
            node = child
        self.lookups += 1
        if matched:
            self.hits += 1
            self.tokens_saved += len(matched) * C
            stamp = next(self._clock)
            for n in matched:
                n.pins += 1
                n.stamp = stamp
        return len(matched) * C, matched

    def release(self, nodes: Sequence[TrieNode]):
        """Unpin a match (the spans are copied, eviction may proceed)."""
        for n in nodes:
            if n.pins > 0:
                n.pins -= 1

    def peek(self, tokens: Sequence[int]) -> int:
        """Longest capped match length WITHOUT pinning, LRU touch, or
        hit/lookup accounting — the read the disagg admission path and
        routing decisions use (``match`` is reserved for admissions that
        will actually copy the blocks out)."""
        C = self.chunk_size
        limit = max(0, (len(tokens) - 1)) // C
        node = self._root
        depth = 0
        for c in range(limit):
            child = node.children.get(
                tuple(int(t) for t in tokens[c * C:(c + 1) * C]))
            if child is None:
                break
            node = child
            depth += 1
        return depth * C

    def walk(self, tokens: Sequence[int], n_chunks: int) -> List[TrieNode]:
        """PINNED nodes for the first ``n_chunks`` chunks of ``tokens``
        present in the trie (contiguous from the root, no one-token-short
        cap, no hit/lookup stats) — the KV-export path: the caller copies
        each node's block out of the pool and then ``release()``s. Unlike
        ``match`` this may cover the whole prompt: the importing engine
        applies its own admission cap."""
        C = self.chunk_size
        node = self._root
        out: List[TrieNode] = []
        for c in range(max(0, int(n_chunks))):
            child = node.children.get(
                tuple(int(t) for t in tokens[c * C:(c + 1) * C]))
            if child is None:
                break
            out.append(child)
            node = child
        if out:
            stamp = next(self._clock)
            for n in out:
                n.pins += 1
                n.stamp = stamp
        return out

    def covered_fp(self, tokens: Sequence[int], n_chunks: int
                   ) -> Optional[int]:
        """The path fingerprint of the DEEPEST trie node actually
        covering the first ``n_chunks`` chunks of ``tokens`` (None when
        even the first chunk is absent). The KV-fabric export path uses
        this to verify a peer's requested fingerprint against live trie
        state: a GCS summary is a push-cadence snapshot, so it can name
        blocks this replica has since evicted — the exporter must prove
        the fingerprint before shipping spans, or the importer would
        install KV for the wrong tokens. Stat-free and unpinned (the
        subsequent ``walk`` pins)."""
        C = self.chunk_size
        node = self._root
        fp = None
        for c in range(max(0, int(n_chunks))):
            child = node.children.get(
                tuple(int(t) for t in tokens[c * C:(c + 1) * C]))
            if child is None:
                break
            fp = child.fp
            node = child
        return fp

    def summary(self, top_k: int = 128) -> Dict[str, Any]:
        """Compact trie summary for cluster-wide prefix routing: the
        ``top_k`` most-recently-touched nodes' path fingerprints (plus
        the chunk size the fingerprints were computed at). A router
        holding summaries from every replica answers "which replica
        covers this prompt deepest" by intersecting the prompt's own
        ``chunk_fingerprints`` with each set — no tokens leave the
        replica, and the payload is ~8 bytes per cached chunk."""
        rows: List[Tuple[int, int]] = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            rows.append((n.stamp, n.fp))
            stack.extend(n.children.values())
        rows.sort(reverse=True)
        return {"fps": [fp for _, fp in rows[:max(0, int(top_k))]],
                "chunk": self.chunk_size,
                "blocks": self.blocks_cached}

    # ------------------------------------------------------------- insert
    def insert(self, tokens: Sequence[int]) -> List[Tuple[int, int]]:
        """Extend the trie over every FULL chunk of ``tokens``. Returns
        ``[(token_offset, block_id), ...]`` for the newly created nodes —
        the caller must fill each block with the KV span at that offset
        before the next engine step. Chunks already present are skipped
        (their KV is identical by construction). Stops at the first
        chunk for which no block can be allocated: the trie only ever
        holds contiguous-from-root prefixes."""
        C = self.chunk_size
        node = self._root
        created: List[Tuple[int, int]] = []
        path: List[TrieNode] = []
        try:
            for c in range(len(tokens) // C):
                key = tuple(int(t) for t in tokens[c * C:(c + 1) * C])
                child = node.children.get(key)
                if child is None:
                    block = self._alloc()
                    if block is None:
                        break
                    child = TrieNode(key, block, node)
                    child.fp = _roll_fp(node.fp, key)
                    node.children[key] = child
                    self.blocks_cached += 1
                    created.append((c * C, block))
                # pin the walked path so a later alloc in THIS insert
                # can never evict a node we just created or rely on
                child.pins += 1
                path.append(child)
                child.stamp = next(self._clock)
                node = child
        finally:
            for n in path:
                n.pins -= 1
        return created

    # ----------------------------------------------------------- eviction
    def _alloc(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        victim = self._lru_unpinned_leaf()
        if victim is None:
            return None
        self._detach(victim)
        self.evictions += 1
        return victim.block

    def _lru_unpinned_leaf(self) -> Optional[TrieNode]:
        best: Optional[TrieNode] = None
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif n.pins == 0 and (best is None or n.stamp < best.stamp):
                best = n
        return best

    def _detach(self, node: TrieNode):
        if node.parent is not None:
            node.parent.children.pop(node.key, None)
        node.parent = None
        self.blocks_cached -= 1

    def evict_blocks(self, n: int) -> int:
        """Shed up to ``n`` LRU unpinned leaf blocks back to the free
        list (slot-pressure hook). Returns the number actually freed."""
        freed = 0
        for _ in range(n):
            victim = self._lru_unpinned_leaf()
            if victim is None:
                break
            self._detach(victim)
            self._free.append(victim.block)
            self.evictions += 1
            freed += 1
        return freed

    # -------------------------------------------------------------- stats
    def __len__(self) -> int:
        return self.blocks_cached

    @property
    def hit_rate(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    def stats(self) -> Dict[str, Any]:
        return {
            "prefix_lookups": self.lookups,
            "prefix_hits": self.hits,
            "prefix_hit_rate": round(self.hit_rate, 4),
            "prefix_tokens_saved": self.tokens_saved,
            "prefix_blocks_cached": self.blocks_cached,
            "prefix_blocks_free": len(self._free),
            "prefix_evictions": self.evictions,
        }
