"""TPU-native continuous-batching LLM inference (slot-pool KV cache,
chunked prefill under a token budget, persistent one-compile decode
loop, per-request token streaming). See engine.py for the architecture,
api.py for the Serve integration."""

from ray_tpu.inference.engine import EngineConfig, InferenceEngine
from ray_tpu.inference.prefix_cache import RadixPrefixCache
from ray_tpu.inference.scheduler import (FINISH_CANCELLED, FINISH_DEADLINE,
                                         FINISH_EOS, FINISH_LENGTH,
                                         Request, RequestHandle, Scheduler)
from ray_tpu.inference.api import LLMDeployment
from ray_tpu.inference.spec_decode import SpecDecodeConfig
from ray_tpu.inference.kv_quant import slot_gain as kv_quant_slot_gain

__all__ = ["EngineConfig", "InferenceEngine", "LLMDeployment",
           "RadixPrefixCache", "Request", "RequestHandle", "Scheduler",
           "SpecDecodeConfig", "kv_quant_slot_gain",
           "FINISH_CANCELLED", "FINISH_DEADLINE", "FINISH_EOS",
           "FINISH_LENGTH"]
