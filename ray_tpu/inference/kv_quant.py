"""int8 KV-cache quantization (ROADMAP item 1: ~double the slot count
per HBM byte).

Scheme: symmetric per-row int8 over the head dimension — each
``[..., D]`` row of a K or V span quantizes independently with
``scale = amax / 127`` (a zero row keeps scale 1.0 so dequantize is
exact), stored as ``int8[..., D]`` values plus an ``fp32[...]`` scale
array that drops the last axis. Per-(position, head) scales are the
finest granularity that adds no matmul work on the read path: the
engine dequantizes a span in one fused multiply when it loads it back
into fp scratch, and attention itself never sees int8.

Where it plugs in (ray_tpu/inference/engine.py, kv_quant="int8"):

- the prefix-cache BLOCK pool stores int8 + scales; ``save_span`` /
  ``load_span`` gain quantizing/dequantizing variants (still
  fixed-shape, still compile-once);
- the decode slot pool and prefill scratch stay full precision — the
  pool is donated through the one decode program and rewriting it as
  int8 would put a quantize/dequantize pair on the per-token hot path
  for zero capacity win (slots are transient; blocks are the cache);
- to keep greedy output bit-identical between a prefix-cache HIT and
  MISS, the miss path publishes each completed chunk and immediately
  reloads the dequantized values into its own scratch, so both paths
  attend over exactly the same (once-quantized) numbers;
- the disagg hand-off (serve/disagg.py) ships int8 spans + scales —
  the wire payload shrinks by ~``itemsize * D / (D + 4)``.

Host (numpy) variants mirror the jnp math bit-for-bit (same round/clip
on the same fp32 inputs) for cross-mode hand-offs: an fp16 exporter
feeding an int8 importer quantizes on the host with identical results.
"""

from __future__ import annotations

import numpy as np

VALID_MODES = ("none", "int8")


def check_mode(mode) -> str:
    mode = mode or "none"
    if mode not in VALID_MODES:
        raise ValueError(f"kv_quant={mode!r}; expected one of "
                         f"{VALID_MODES}")
    return mode


def quantize_kv(x):
    """jnp: fp[..., D] -> (int8[..., D], fp32 scale[...])."""
    import jax.numpy as jnp
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0.0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def dequantize_kv(q, scale, dtype):
    """jnp inverse: (int8[..., D], fp32[...]) -> dtype[..., D]."""
    import jax.numpy as jnp
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def quantize_kv_np(x):
    """Host mirror of :func:`quantize_kv` (same fp32 math)."""
    xf = np.asarray(x, np.float32)
    amax = np.max(np.abs(xf), axis=-1)
    scale = np.where(amax > 0.0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(xf / scale[..., None]), -127.0, 127.0)
    return q.astype(np.int8), scale


def dequantize_kv_np(q, scale, dtype=np.float32):
    """Host mirror of :func:`dequantize_kv`."""
    return (np.asarray(q, np.float32)
            * np.asarray(scale, np.float32)[..., None]).astype(dtype)


def int8_block_bytes_per_token(n_kv_heads: int, head_dim: int) -> int:
    """Bytes one cached token position costs in the int8 block pool
    (K + V values + their scale rows)."""
    return 2 * n_kv_heads * (head_dim + 4)


def fp_block_bytes_per_token(n_kv_heads: int, head_dim: int,
                             itemsize: int) -> int:
    """Same position's cost at full precision (K + V)."""
    return 2 * n_kv_heads * head_dim * itemsize


def slot_gain(head_dim: int, fp_itemsize: int) -> float:
    """Capacity multiplier of int8 blocks vs ``fp_itemsize``-byte
    blocks at equal HBM: ``itemsize * D / (D + 4)`` (the +4 is the
    fp32 scale per row). ~1.94x for fp16 at D=128, ~3.88x for fp32."""
    return fp_itemsize * head_dim / float(head_dim + 4)
