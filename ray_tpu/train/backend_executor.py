"""Worker-group orchestration for distributed training (reference:
python/ray/train/_internal/backend_executor.py:68 BackendExecutor +
_internal/worker_group.py:102 WorkerGroup).

A training run = a placement group (gang) + one actor per worker +
rank/world wiring + a backend hook that initializes jax.distributed
(coordinator rendezvous through GCS KV — the NCCL/TCP-store replacement).
Worker failures surface as ActorDiedError on the run refs. Restart
granularity follows ``FailureConfig.restart_policy``: under "job" the
trainer restarts the whole gang from the latest checkpoint; under
"stage" the executor replaces ONLY the dead workers in place
(:meth:`BackendExecutor.replace_failed_workers` — same bundle, same
rank, latest-checkpoint resume pushed to the fresh actor) while the
survivors keep running. Per-worker replace is refused (job restart
instead) when the gang runs jax.distributed collectives or a slice
topology — those fail as a unit."""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext, _init_session
from ray_tpu.util import (PlacementGroupSchedulingStrategy, placement_group,
                          remove_placement_group)


class TrainWorker:
    """Actor hosting one training worker (needs max_concurrency=2 so
    poll()/get_address() answer while run() blocks)."""

    def __init__(self):
        self._session = None
        self._context = None

    def setup(self, world_size: int, rank: int, local_rank: int,
              node_rank: int):
        self._context = TrainContext(world_size=world_size, world_rank=rank,
                                     local_rank=local_rank,
                                     node_rank=node_rank)
        self._session = _init_session(self._context)
        return True

    def set_resume_checkpoint(self, ckpt):
        if self._session is not None:
            self._session.latest_checkpoint = ckpt
        return True

    def set_dataset_shards(self, shards):
        if self._session is not None:
            self._session.dataset_shards = shards
        return True

    def get_node_ip(self):
        from ray_tpu._private.rpc import node_ip_address
        return node_ip_address()

    def get_node_id(self):
        from ray_tpu._private.worker import global_worker
        return global_worker.core.node_id

    def setup_jax_distributed(self, group_name: str, world_size: int,
                              rank: int):
        # rank 0 binds a free port on ITS host and publishes via GCS KV
        # (the collective rendezvous helper), so no port guessing
        from ray_tpu.util.collective import _init_jax_distributed
        _init_jax_distributed(world_size, rank, group_name)
        return True

    def run(self, fn, config):
        import inspect
        try:
            takes_arg = len(inspect.signature(fn).parameters) >= 1
        except (TypeError, ValueError):
            takes_arg = config is not None
        if takes_arg:
            fn(config if config is not None else {})
        else:
            fn()
        return True

    def poll(self):
        if self._session is None:
            return []
        return self._session.drain()

    def ping(self):
        return True


def acquire_slice_bundles(topology: str,
                          worker_resources: Dict[str, float],
                          num_workers: Optional[int] = None,
                          wait_timeout_s: Optional[float] = None):
    """Wait for a whole healthy multi-host slice and return
    ``(pod, bundles, "STRICT_SPREAD")`` — the slice-gang acquisition
    shared by :meth:`BackendExecutor.start` and the MPMD stage gangs
    (``train.mpmd.GangStageHandle``), where one pipeline stage is a gang
    of workers over one multi-host mesh. Competing gangs / restarting
    nodes make slice availability transient; staying in the wait keeps
    the demand visible instead of burning the caller's failure budget
    instantly. Returns ``(None, None, None)`` for single-host
    topologies (no gang needed)."""
    from ray_tpu.train import slice as slice_lib
    n_hosts, chips = slice_lib.slice_shape(topology)
    if n_hosts <= 1:
        return None, None, None
    if num_workers is not None and num_workers != n_hosts:
        raise ValueError(
            f"topology {topology} has {n_hosts} hosts; "
            f"num_workers={num_workers} must match")
    from ray_tpu._private.config import cfg as _cfg
    deadline = time.monotonic() + (
        wait_timeout_s if wait_timeout_s is not None
        else _cfg.slice_wait_timeout_s)
    pod = None
    while pod is None:
        pod = slice_lib.pick_slice(ray_tpu.nodes(), topology)
        if pod is None:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no healthy {topology} slice available "
                    f"({n_hosts} hosts with {chips} free chips each)")
            time.sleep(1.0)
    bundles = slice_lib.slice_bundles(pod, topology, worker_resources)
    return pod, bundles, "STRICT_SPREAD"


class BackendExecutor:
    def __init__(self, scaling_config: ScalingConfig,
                 use_jax_distributed: bool = False):
        self.scaling = scaling_config
        self.use_jax_distributed = use_jax_distributed
        self.pg = None
        self.workers: List = []
        self.run_refs: List = []
        self.slice_pod = None
        self._bundles: List[Dict] = []
        self._dataset_shards = None
        self._run_fn = None
        self._run_config = None

    def start(self):
        n = self.scaling.num_workers
        res = self.scaling.worker_resources()
        strategy = self.scaling.placement_strategy
        bundles = [dict(res) for _ in range(n)]
        topology = self.scaling.topology
        if topology:
            # slice gang: one worker per slice host, pinned to ONE healthy
            # slice via its pod resource, STRICT_SPREAD across its hosts
            # (fails-as-a-unit semantics come from the trainer restarting
            # the whole gang on any worker/node death)
            try:
                pod, slice_bundles, slice_strategy = acquire_slice_bundles(
                    topology, res, num_workers=n)
            except ValueError as e:
                raise ValueError(str(e).replace(
                    "num_workers", "ScalingConfig.num_workers")) from None
            if pod is not None:
                bundles = slice_bundles
                strategy = slice_strategy
                self.slice_pod = pod
        self.pg = placement_group(bundles, strategy=strategy)
        if not self.pg.wait(timeout=60):
            remove_placement_group(self.pg)
            raise RuntimeError(
                f"placement group for {bundles} not schedulable")
        self._bundles = bundles
        self.workers = [self._spawn_worker(i) for i in range(n)]
        # ranks: worker order; local/node ranks by node ip grouping
        ips = ray_tpu.get([w.get_node_ip.remote() for w in self.workers],
                          timeout=120)
        node_order: Dict[str, int] = {}
        local_counters: Dict[str, int] = {}
        setups = []
        self._setup_args: List[tuple] = []
        for rank, (w, ip) in enumerate(zip(self.workers, ips)):
            node_rank = node_order.setdefault(ip, len(node_order))
            local_rank = local_counters.get(ip, 0)
            local_counters[ip] = local_rank + 1
            self._setup_args.append((n, rank, local_rank, node_rank))
            setups.append(w.setup.remote(n, rank, local_rank, node_rank))
        ray_tpu.get(setups, timeout=120)
        if self.use_jax_distributed:
            import uuid
            group = f"train-{uuid.uuid4().hex[:8]}"
            ray_tpu.get([w.setup_jax_distributed.remote(group, n, r)
                         for r, w in enumerate(self.workers)], timeout=300)

    def set_resume_checkpoint(self, ckpt):
        ray_tpu.get([w.set_resume_checkpoint.remote(ckpt)
                     for w in self.workers], timeout=60)

    def setup_datasets(self, datasets, data_config=None):
        """Streaming-split datasets across the worker gang (reference:
        DataConfig streaming split into Train, _internal/data_config.py:
        one executing stream per dataset, one disjoint shard per worker)."""
        from ray_tpu.data.split import streaming_split
        split_names = getattr(data_config, "datasets_to_split", "all") \
            if data_config is not None else "all"
        n = len(self.workers)
        # locality hints (fetched lazily, once, only if a split happens):
        # bundles already resident on a worker's node deal to that
        # worker (split.py locality-aware dealing)
        hints_box: List = []

        def _hints():
            if not hints_box:
                try:
                    hints_box.append(ray_tpu.get(
                        [w.get_node_id.remote() for w in self.workers],
                        timeout=60))
                except Exception:
                    hints_box.append(None)
            return hints_box[0]

        per_worker = {i: {} for i in range(n)}
        for name, ds in datasets.items():
            split = split_names == "all" or name in split_names
            if split and n > 1:
                shards = streaming_split(ds, n, locality_hints=_hints())
                for i in range(n):
                    per_worker[i][name] = shards[i]
            else:
                # replicated: each worker gets its own full stream
                for i in range(n):
                    per_worker[i][name] = streaming_split(ds, 1)[0]
        # the ORIGINAL coordinator handles live in these iterators: they
        # must outlive the run (worker-side copies are non-owning, and
        # dropping the originals would kill the coordinators mid-stream)
        self._dataset_shards = per_worker
        ray_tpu.get([w.set_dataset_shards.remote(per_worker[i])
                     for i, w in enumerate(self.workers)], timeout=120)

    def _spawn_worker(self, bundle_index: int):
        actor_cls = ray_tpu.remote(TrainWorker)
        return actor_cls.options(
            max_concurrency=2,
            resources=dict(self._bundles[bundle_index]),  # consumes bundle
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                self.pg, placement_group_bundle_index=bundle_index),
        ).remote()

    def start_training(self, fn: Callable, config):
        self._run_fn, self._run_config = fn, config
        self.run_refs = [w.run.remote(fn, config) for w in self.workers]
        return self.run_refs

    def poll_results(self) -> List[List[Dict]]:
        """Drain buffered report() rows per worker. A dead worker
        contributes an empty list instead of failing the sweep — its
        death is surfaced by finished() / failed_worker_indexes(), and
        under restart_policy="stage" the survivors' metrics must keep
        flowing while the replacement builds."""
        out: List[List[Dict]] = []
        for w in self.workers:
            try:
                out.append(ray_tpu.get(w.poll.remote(), timeout=60))
            except Exception:
                out.append([])
        return out

    def finished(self):
        """(done, error): done when every run ref resolved; error holds the
        first worker failure."""
        ready, not_ready = ray_tpu.wait(self.run_refs,
                                        num_returns=len(self.run_refs),
                                        timeout=0)
        if not_ready:
            # check for failed ones among ready
            for r in ready:
                try:
                    ray_tpu.get(r, timeout=1)
                except Exception as e:
                    return True, e
            return False, None
        try:
            ray_tpu.get(self.run_refs, timeout=5)
            return True, None
        except Exception as e:
            return True, e

    # -------------------------------------------------- per-worker replace
    def supports_worker_replace(self) -> bool:
        """Per-worker replace is sound only when workers are independent
        processes: a jax.distributed gang's collectives hang on a member
        swap (the group rendezvous is immutable) and a slice topology
        fails as a unit — both degrade to the job-level restart."""
        return not self.use_jax_distributed and self.slice_pod is None

    def failed_worker_indexes(self) -> List[int]:
        """Workers whose run ref resolved with an error (actor death or
        a raised training loop); survivors' refs stay pending."""
        failed = []
        for i, ref in enumerate(self.run_refs):
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
            if not ready:
                continue
            try:
                ray_tpu.get(ref, timeout=1)
            except Exception:
                failed.append(i)
        return failed

    def replace_failed_workers(self, resume_checkpoint=None) -> List[int]:
        """Build a fresh actor in each dead worker's bundle, re-wire its
        rank, push the latest checkpoint + its dataset shards, and
        restart its training loop — the surviving workers never stop.
        Returns the replaced indexes (empty when nothing was dead or
        replace is unsupported)."""
        if not self.supports_worker_replace():
            return []
        failed = self.failed_worker_indexes()
        if not failed:
            return []
        from ray_tpu._private import events
        for i in failed:
            try:
                ray_tpu.kill(self.workers[i])
            except Exception:
                pass   # already dead
            w = self._spawn_worker(i)
            ray_tpu.get(w.setup.remote(*self._setup_args[i]), timeout=120)
            if resume_checkpoint is not None:
                ray_tpu.get(w.set_resume_checkpoint.remote(
                    resume_checkpoint), timeout=60)
            if self._dataset_shards is not None:
                ray_tpu.get(w.set_dataset_shards.remote(
                    self._dataset_shards[i]), timeout=120)
            self.workers[i] = w
            self.run_refs[i] = w.run.remote(self._run_fn, self._run_config)
            events.record_instant(
                "train.worker_replaced", category="train", rank=i,
                resumed=bool(resume_checkpoint is not None))
        return failed

    def shutdown(self):
        self._dataset_shards = None
        self.run_refs = []
        # gang teardown: surviving workers of a partially-failed slice
        # must die with it (a half-dead slice can't run collectives and
        # its actors would leak leases + chips otherwise)
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None
