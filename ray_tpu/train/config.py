"""Train config dataclasses (reference: python/ray/air/config.py —
ScalingConfig, RunConfig, FailureConfig, CheckpointConfig). ScalingConfig
gains TPU topology/mesh axes: the mesh is a first-class training knob here,
compiled into NamedShardings (reference leaves this to torch FSDP inside
the loop)."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from ray_tpu.parallel.mesh import MeshConfig


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    topology: Optional[str] = None        # e.g. "v5litepod-8", "v4-32"
    mesh: Optional[MeshConfig] = None     # per-worker device mesh axes
    placement_strategy: str = "PACK"
    # None -> follow use_tpu; True forces the jax.distributed rendezvous
    # even on CPU workers (multi-process CPU collectives, used in CI)
    use_jax_distributed: Optional[bool] = None

    def jax_distributed_enabled(self) -> bool:
        """Explicit True/False wins (even for one worker); default follows
        use_tpu, where a single-worker run needs no rendezvous."""
        if self.use_jax_distributed is not None:
            return self.use_jax_distributed
        return self.use_tpu and self.num_workers > 1

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        if "CPU" not in res:
            res["CPU"] = 1.0
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = 1.0
        return res


@dataclasses.dataclass
class DataConfig:
    """Which datasets are streaming-split across train workers; others are
    replicated per worker (reference: ray.train DataConfig,
    python/ray/train/_internal/data_config.py)."""
    datasets_to_split: Any = "all"      # "all" | list of names


@dataclasses.dataclass
class FailureConfig:
    """Failure budget + restart granularity for training runs.

    restart_policy:
      "job"   — any worker death restarts the WHOLE gang from the latest
                checkpoint (the only safe granularity for jax.distributed
                collectives and TPU slices, which fail as a unit).
      "stage" — only the dead party restarts: JaxTrainer replaces the
                failed worker in place (BackendExecutor per-worker
                replace, latest-checkpoint resume pushed to it) and the
                MPMD pipeline trainer replaces the lost STAGE (park →
                restore shard → replay) while survivors keep their
                state. Falls back to job-level restart where per-worker
                replace is unsound (jax.distributed gangs, slice
                topologies).
    restart_backoff_s: delay before any restart/replace attempt.
    """
    max_failures: int = 0
    restart_policy: str = "job"
    restart_backoff_s: float = 1.0

    def __post_init__(self):
        if self.restart_policy not in ("job", "stage"):
            raise ValueError(
                f"restart_policy must be 'job' or 'stage', "
                f"got {self.restart_policy!r}")
        if self.restart_backoff_s < 0:
            raise ValueError("restart_backoff_s must be >= 0")


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
