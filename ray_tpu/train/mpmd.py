"""Elastic MPMD pipeline training: per-stage programs on per-stage
meshes, activations over the data plane, stage-level preemption recovery.

The SPMD pipeline (parallel/pipeline.py) keeps every stage inside ONE
jitted program on one mesh — the right shape inside a slice, but it
cannot span slices (no ICI) and a single lost host kills the whole
program. This module is the cross-slice shape from the MPMD pipeline
paper (arXiv 2412.14374): each stage is its OWN program compiled once on
its OWN mesh/slice, hosted by an actor; activations and input-gradients
hop stage-to-stage as object-store objects — created in the pinned
shared-memory arena by the producing actor and, across nodes, shipped by
the PR 5 zero-copy binary data plane (the controller only routes refs,
bytes never visit it). The microbatch schedule (1F1B by default, GPipe
optional — parallel/pipeline.py schedule_*) is dispatched ref-chained:
every op of a step is submitted up front and the per-actor ordered
queues + object dependencies realize the pipeline without a host round
trip per hop.

Stage loss is a first-class lifecycle, mirroring PR 9's serving shape:

  notice   — each stage actor watches ``tpu.check_preemption_notice()``
             (plus its per-stage marker file, the chaos channel); a
             preempting stage is migrated at the NEXT step boundary:
             fresh shard checkpoint, replacement provisioned, old actor
             reaped — zero steps replayed.
  crash    — a stage actor that dies mid-step (preemption without
             notice, chaos ``StageKiller``) surfaces as failed applies /
             dead pings. Surviving stages PARK at a bounded-deadline
             barrier (abort the in-flight step, roll back to the last
             checkpoint boundary — their params never left the process);
             the controller re-provisions the stage from its shard
             checkpoint (object-store snapshot ref first; storage shard
             via ``sharded_checkpoint.restore_and_broadcast`` when a
             ``storage_path`` is configured and the ref is gone), then
             REPLAYS the buffered input microbatches. Replay re-runs the
             identical per-stage op order through the identical
             compiled-once programs, so post-replay optimizer state is
             bit-identical to an uninterrupted run; training resumes
             within ``replay_depth + 1`` steps of where it stopped.
  degrade  — a survivor that misses the park barrier
             (``mpmd_barrier_deadline_s``) or an exhausted
             ``FailureConfig.max_failures`` budget raises
             :class:`PipelineDegradedError`; the job-level
             ``restart_policy="job"`` ladder (trainer.py) takes over.

Compile-once discipline (the engine's ``decode`` rule applied to
training): each stage jits exactly one forward, one backward, one
grad-accumulate and one optimizer-apply program for its life; the
counters are asserted ==1 across recovery — survivors never retrace and
a replacement compiles each program exactly once in its fresh process.

Unit-tier shape: the controller talks to stages through a handle
protocol; :class:`LocalStageHandle` runs stages in-process (tests,
probes, the MULTICHIP dryrun with per-stage device subsets) while
:class:`ActorStageHandle` wraps a :class:`PipelineStageActor` gang —
same dispatcher, same recovery path.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu._private.config import cfg
from ray_tpu.parallel.pipeline import (OP_BWD, OP_FWD, make_schedule,
                                       peak_live_activations,
                                       pipeline_bubble_fraction)
from ray_tpu.train.config import FailureConfig


class StageLostError(RuntimeError):
    """One or more stage actors died or failed mid-step; carries the
    lost stage indexes (a single chaos event can take several stages —
    e.g. a node death under two colocated stages)."""

    def __init__(self, stage_idx: int, cause: str = "",
                 stages: Optional[List[int]] = None):
        self.stages = sorted(set(stages or [stage_idx]))
        super().__init__(f"pipeline stage(s) {self.stages} lost"
                         + (f": {cause}" if cause else ""))
        self.stage_idx = stage_idx
        self.cause = cause


class PipelineDegradedError(RuntimeError):
    """Stage-level recovery could not proceed (park-barrier deadline
    missed or failure budget exhausted); the pipeline is parked and the
    caller must fall back to a job-level restart."""


@dataclasses.dataclass
class StageDefinition:
    """What one pipeline stage computes. Built INSIDE the stage's
    process by the per-stage builder so params land on the stage's own
    mesh/devices.

    stage_fn(params, x) -> y; the last stage's ``loss_fn(y, targets)``
    -> scalar closes the pipeline. ``place`` re-places a restored host
    (numpy) state tree onto the stage's devices/shardings (defaults to
    leaving host arrays for jit to commit)."""
    stage_fn: Callable[[Any, Any], Any]
    params: Any
    optimizer: Any                                  # optax gradient xform
    loss_fn: Optional[Callable[[Any, Any], Any]] = None
    place: Optional[Callable[[Any], Any]] = None


@dataclasses.dataclass
class MPMDConfig:
    """Pipeline-shape + elasticity knobs (defaults from the flag
    registry, overridable per trainer)."""
    n_microbatches: int = 4
    schedule: str = "1f1b"                  # "1f1b" | "gpipe"
    replay_depth: Optional[int] = None      # cfg.mpmd_replay_depth
    checkpoint_every: Optional[int] = None  # default: replay_depth
    barrier_deadline_s: Optional[float] = None
    step_timeout_s: Optional[float] = None
    storage_path: Optional[str] = None      # durable shard checkpoints

    def resolved(self) -> "MPMDConfig":
        c = dataclasses.replace(self)
        if c.replay_depth is None:
            c.replay_depth = cfg.mpmd_replay_depth
        if c.checkpoint_every is None:
            c.checkpoint_every = c.replay_depth
        if c.barrier_deadline_s is None:
            c.barrier_deadline_s = cfg.mpmd_barrier_deadline_s
        if c.step_timeout_s is None:
            c.step_timeout_s = cfg.mpmd_step_timeout_s
        if c.n_microbatches < 1:
            raise ValueError("n_microbatches must be >= 1")
        if c.replay_depth < 1:
            raise ValueError("replay_depth must be >= 1")
        if c.checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        if c.checkpoint_every > c.replay_depth:
            raise ValueError(
                f"checkpoint_every={c.checkpoint_every} must be <= "
                f"replay_depth={c.replay_depth}: the replay buffer must "
                "cover every step since the last shard checkpoint")
        return c


# ------------------------------------------------------------ replay buffer

class MicrobatchReplayBuffer:
    """Bounded per-step retention of input microbatches (+ targets) so a
    re-provisioned stage can replay every step since the last shard
    checkpoint. Eviction is deterministic: strictly oldest-first once
    more than ``depth`` steps are held. Stored arrays are snapshotted
    (np.asarray copies) so later caller mutation can't corrupt replay."""

    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError("replay depth must be >= 1")
        self.depth = depth
        self._steps: Dict[int, Any] = {}

    def record(self, step: int, inputs: List[Any], targets: List[Any]):
        self._steps[step] = (
            [np.array(np.asarray(x)) for x in inputs],
            [np.array(np.asarray(t)) for t in targets])
        while len(self._steps) > self.depth:
            del self._steps[min(self._steps)]

    def steps(self) -> List[int]:
        return sorted(self._steps)

    def get(self, step: int):
        if step not in self._steps:
            raise KeyError(
                f"step {step} not in replay buffer (held: {self.steps()}, "
                f"depth {self.depth})")
        return self._steps[step]

    def replayable_from(self, boundary_step: int) -> List[int]:
        """Steps after ``boundary_step`` available for replay, in order;
        raises if a gap means the boundary is too old to recover from."""
        want = [s for s in self.steps() if s > boundary_step]
        expect = list(range(boundary_step + 1, boundary_step + 1 + len(want)))
        if want != expect:
            raise KeyError(
                f"replay gap: checkpoint at step {boundary_step} but "
                f"buffer holds {self.steps()}")
        return want


# ------------------------------------------------------------ stage runtime

class StageRuntime:
    """One stage's compute engine: compile-once fwd/bwd/accumulate/apply
    programs over the StageDefinition, saved-input bookkeeping for the
    recompute-style backward, grad accumulation in schedule order (replay
    determinism), and host-snapshot checkpoint/rollback. Runs unchanged
    inside a :class:`PipelineStageActor` or a :class:`LocalStageHandle`."""

    def __init__(self, defn: StageDefinition, *, stage_idx: int,
                 n_stages: int, n_microbatches: int):
        import jax

        self.defn = defn
        self.stage_idx = stage_idx
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.is_first = stage_idx == 0
        self.is_last = stage_idx == n_stages - 1
        if self.is_last and defn.loss_fn is None:
            raise ValueError("last stage needs a loss_fn")
        self.step = 0
        self.params = defn.params
        self.opt_state = defn.optimizer.init(defn.params)
        self.fwd_compile_count = 0
        self.bwd_compile_count = 0
        self.apply_compile_count = 0
        self._saved: Dict[tuple, Any] = {}
        self._gacc = None
        self._losses: List[Any] = []
        self._compute_s = 0.0
        self._last_snapshot = self._host_snapshot()

        stage_fn, loss_fn = defn.stage_fn, defn.loss_fn
        M = n_microbatches

        def fwd(params, x):
            self.fwd_compile_count += 1       # trace-time only
            return stage_fn(params, x)

        def fwd_last(params, x, target):
            self.fwd_compile_count += 1
            return loss_fn(stage_fn(params, x), target)

        def bwd(params, x, gy):
            self.bwd_compile_count += 1
            _y, vjp = jax.vjp(stage_fn, params, x)
            gp, gx = vjp(gy)
            return gx, gp

        def bwd_last(params, x, target):
            self.bwd_compile_count += 1
            loss, (gp, gx) = jax.value_and_grad(
                lambda p, xx: loss_fn(stage_fn(p, xx), target),
                argnums=(0, 1))(params, x)
            return gx, gp, loss

        def acc(a, b):
            return jax.tree.map(lambda u, v: u + v, a, b)

        def apply(params, opt_state, gacc):
            self.apply_compile_count += 1
            g = jax.tree.map(lambda u: u / M, gacc)
            updates, new_opt = defn.optimizer.update(g, opt_state,
                                                     params=params)
            import optax
            return optax.apply_updates(params, updates), new_opt

        self._fwd_j = jax.jit(fwd_last if self.is_last else fwd)
        self._bwd_j = jax.jit(bwd_last if self.is_last else bwd)
        self._acc_j = jax.jit(acc)
        self._apply_j = jax.jit(apply)

    # ------------------------------------------------------------- compute
    def _timed(self, fn, *args):
        import jax
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        self._compute_s += time.perf_counter() - t0
        return out

    def forward(self, step: int, mb: int, x, target=None):
        """Run F(step, mb). Non-last stages return the activation (the
        object the next stage consumes); the last stage returns its
        per-microbatch loss. The input is saved for the recompute-style
        backward and dropped by it (or by abort_step). Outputs cross a
        MESH boundary, so they leave as host arrays — in-process that is
        the device→host hop the object-store hand-off pays anyway, and
        it keeps each stage's program free of the neighbor's placement."""
        if self.is_last:
            self._saved[(step, mb)] = (x, target)
            return np.asarray(self._timed(self._fwd_j, self.params, x,
                                          target))
        self._saved[(step, mb)] = x
        return np.asarray(self._timed(self._fwd_j, self.params, x))

    def backward(self, step: int, mb: int, gy=None):
        """Run B(step, mb): recompute-vjp over the saved input,
        accumulate param grads IN CALL ORDER (the schedule's order —
        replay hits the same order, hence bit-identical accumulation),
        return the input-gradient for the upstream stage (host array —
        it crosses the mesh boundary too)."""
        if self.is_last:
            x, target = self._saved.pop((step, mb))
            gx, gp, loss = self._timed(self._bwd_j, self.params, x, target)
            self._losses.append(np.asarray(loss))
        else:
            x = self._saved.pop((step, mb))
            gx, gp = self._timed(self._bwd_j, self.params, x, gy)
        self._gacc = gp if self._gacc is None \
            else self._acc_j(self._gacc, gp)
        return np.asarray(gx)

    def apply_step(self, step: int) -> Dict[str, Any]:
        """Step boundary: apply the accumulated (mean) gradient, clear
        per-step state, return stage metrics."""
        if self._gacc is None:
            raise RuntimeError(f"stage {self.stage_idx}: apply_step({step}) "
                               "with no accumulated gradients")
        if self._saved:
            raise RuntimeError(
                f"stage {self.stage_idx}: {len(self._saved)} saved "
                f"activations outstanding at apply_step({step})")
        self.params, self.opt_state = self._timed(
            self._apply_j, self.params, self.opt_state, self._gacc)
        metrics: Dict[str, Any] = {
            "step": step, "stage": self.stage_idx,
            "compute_s": round(self._compute_s, 6),
            "fwd_compile_count": self.fwd_compile_count,
            "bwd_compile_count": self.bwd_compile_count,
        }
        if self.is_last and self._losses:
            metrics["loss"] = float(np.mean([np.asarray(l)
                                             for l in self._losses]))
        self._gacc = None
        self._losses = []
        self._compute_s = 0.0
        self.step = step
        return metrics

    def abort_step(self, step: int) -> bool:
        """Park: drop the in-flight step's saved activations, partial
        grad accumulation and losses. Params/opt_state are untouched —
        they only move at apply_step."""
        self._saved = {k: v for k, v in self._saved.items()
                       if k[0] != step}
        self._gacc = None
        self._losses = []
        self._compute_s = 0.0
        return True

    # ------------------------------------------------------- checkpointing
    def _host_snapshot(self) -> Dict[str, Any]:
        import jax
        return {"step": self.step,
                "stage": self.stage_idx,
                "params": jax.tree.map(lambda a: np.asarray(a), self.params),
                "opt_state": jax.tree.map(lambda a: np.asarray(a),
                                          self.opt_state)}

    def checkpoint(self, step: int) -> Dict[str, Any]:
        """Record a step-boundary shard snapshot (host arrays). Kept
        in-process for local rollback; the caller also parks a copy in
        the object store so a REPLACEMENT stage can restore it."""
        if step != self.step:
            raise RuntimeError(
                f"stage {self.stage_idx}: checkpoint({step}) at "
                f"step {self.step} — checkpoints are step-boundary only")
        self._last_snapshot = self._host_snapshot()
        return self._last_snapshot

    def rollback(self) -> int:
        """Roll params/opt_state back to the last checkpoint boundary;
        returns the boundary step."""
        self.load_snapshot(self._last_snapshot)
        return self.step

    def load_snapshot(self, snap: Dict[str, Any]):
        place = self.defn.place or (lambda t: t)
        self.params = place(snap["params"])
        self.opt_state = place(snap["opt_state"])
        self.step = int(snap["step"])
        self._last_snapshot = snap
        self._saved = {}
        self._gacc = None
        self._losses = []

    def state_digest(self) -> str:
        """sha256 over every params/opt_state leaf — the bit-identity
        probe the elastic tests compare against an uninterrupted run."""
        import jax
        h = hashlib.sha256()
        for tree in (self.params, self.opt_state):
            for leaf in jax.tree.leaves(tree):
                a = np.asarray(leaf)
                h.update(str(a.dtype).encode())
                h.update(str(a.shape).encode())
                h.update(a.tobytes())
        return h.hexdigest()

    def compile_counts(self) -> Dict[str, int]:
        return {"fwd": self.fwd_compile_count,
                "bwd": self.bwd_compile_count,
                "apply": self.apply_compile_count}


# ------------------------------------------------------------- stage hosts

def _build_definition(builder: Callable, stage_idx: int) -> StageDefinition:
    """Builders may take (stage_idx) or nothing."""
    try:
        import inspect
        takes_arg = len(inspect.signature(builder).parameters) >= 1
    except (TypeError, ValueError):
        takes_arg = True
    defn = builder(stage_idx) if takes_arg else builder()
    if not isinstance(defn, StageDefinition):
        raise TypeError(f"stage builder must return StageDefinition, "
                        f"got {type(defn)!r}")
    return defn


class _Now:
    """Pre-resolved 'future' for the in-process transport."""
    __slots__ = ("value", "error")

    def __init__(self, value=None, error: Optional[BaseException] = None):
        self.value = value
        self.error = error

    def result(self):
        if self.error is not None:
            raise self.error
        return self.value


class LocalStageHandle:
    """In-process stage host speaking the same protocol as the actor
    transport: every call returns a future (here pre-resolved), chaos
    injection runs at forward/backward entry (``stage_step`` spec — a
    fire marks the handle DEAD and every later call raises StageLostError,
    the in-process analog of a SIGKILLed actor), and ``preempting()``
    polls the per-stage marker file. ``fail_at=(step, op)`` arms a
    deterministic one-shot death for tests/probes."""

    remote = False

    def __init__(self, stage_idx: int, n_stages: int, n_microbatches: int,
                 builder: Callable, snapshot: Optional[Dict] = None,
                 preempt_marker: Optional[str] = None,
                 fail_at: Optional[tuple] = None):
        self.stage_idx = stage_idx
        self._rt = StageRuntime(_build_definition(builder, stage_idx),
                                stage_idx=stage_idx, n_stages=n_stages,
                                n_microbatches=n_microbatches)
        if snapshot is not None:
            self._rt.load_snapshot(snapshot)
        self._marker = preempt_marker
        self._fail_at = fail_at
        self._dead = False

    # ------------------------------------------------------ chaos plumbing
    def _chaos(self, step: int, op: str):
        if self._dead:
            raise StageLostError(self.stage_idx, "stage already dead")
        if self._fail_at is not None and self._fail_at == (step, op):
            self._fail_at = None
            self._dead = True
            raise StageLostError(self.stage_idx,
                                 f"armed failure at step {step} {op}")
        from ray_tpu._private import rpc
        try:
            rpc._maybe_inject_failure("stage_step")
        except rpc.RpcError as e:
            self._dead = True
            raise StageLostError(self.stage_idx, str(e)) from e

    def _call(self, fn, *args) -> _Now:
        try:
            return _Now(fn(*args))
        except BaseException as e:   # surfaced at fetch, like a ref
            return _Now(error=e)

    # ------------------------------------------------------------ protocol
    @staticmethod
    def _unwrap(v):
        # upstream outputs arrive as _Now futures; a poisoned one
        # re-raises the upstream loss here, mirroring how a failed
        # object-ref dependency fails the downstream actor task
        return v.result() if isinstance(v, _Now) else v

    def forward(self, step, mb, x, target=None) -> _Now:
        def run():
            self._chaos(step, OP_FWD)
            return self._rt.forward(step, mb, self._unwrap(x), target)
        return self._call(run)

    def backward(self, step, mb, gy=None) -> _Now:
        def run():
            self._chaos(step, OP_BWD)
            return self._rt.backward(step, mb, self._unwrap(gy))
        return self._call(run)

    def apply_step(self, step) -> _Now:
        def run():
            if self._dead:
                raise StageLostError(self.stage_idx, "stage already dead")
            return self._rt.apply_step(step)
        return self._call(run)

    def abort_step(self, step) -> _Now:
        if self._dead:
            return _Now(error=StageLostError(self.stage_idx, "dead"))
        return self._call(self._rt.abort_step, step)

    def checkpoint(self, step) -> _Now:
        if self._dead:
            return _Now(error=StageLostError(self.stage_idx, "dead"))
        return self._call(self._rt.checkpoint, step)

    def rollback(self) -> _Now:
        if self._dead:
            return _Now(error=StageLostError(self.stage_idx, "dead"))
        return self._call(self._rt.rollback)

    def compile_counts(self) -> _Now:
        return self._call(self._rt.compile_counts)

    def state_digest(self) -> _Now:
        return self._call(self._rt.state_digest)

    def ping(self, timeout: Optional[float] = None) -> bool:
        return not self._dead

    def preempting(self) -> bool:
        if self._dead:
            return False
        if self._marker and os.path.exists(self._marker):
            return True
        from ray_tpu._private.accelerators.tpu import \
            check_preemption_notice
        return check_preemption_notice()

    def kill(self):
        self._dead = True

    def fetch(self, fut: _Now, timeout: Optional[float] = None):
        return fut.result()


class PipelineStageActor:
    """Actor hosting one pipeline stage pinned to its own mesh/slice.
    Compute methods ride the DEFAULT (ordered) concurrency group —
    dispatch order is execution order, which the replay-determinism
    guarantee leans on; control methods (ping/abort/rollback/...)
    declare the ``control`` group so the controller can park or probe a
    stage while compute is queued. Chaos: the ``stage_step`` injection
    SIGKILLs the process mid-step (``util.chaos.StageKiller``), the
    hardest death the recovery path must absorb."""

    def __init__(self, stage_idx: int, n_stages: int, n_microbatches: int,
                 builder: Callable, snapshot: Optional[Dict] = None,
                 preempt_marker: Optional[str] = None):
        self._rt = StageRuntime(_build_definition(builder, stage_idx),
                                stage_idx=stage_idx, n_stages=n_stages,
                                n_microbatches=n_microbatches)
        if snapshot is not None:
            self._rt.load_snapshot(snapshot)
        self._marker = preempt_marker
        self._preempting = False
        self._stop = threading.Event()
        self._watch = threading.Thread(target=self._watch_loop,
                                       name=f"stage-{stage_idx}-watch",
                                       daemon=True)
        self._watch.start()

    def _watch_loop(self):
        from ray_tpu._private.accelerators.tpu import \
            check_preemption_notice
        while not self._stop.is_set():
            try:
                if (self._marker and os.path.exists(self._marker)) \
                        or check_preemption_notice():
                    self._preempting = True
            except Exception:
                pass   # rtlint: disable=RT004 — poll again next tick
            if self._stop.wait(cfg.mpmd_health_poll_s):
                return

    def _chaos(self):
        from ray_tpu._private import rpc
        try:
            rpc._maybe_inject_failure("stage_step")
        except rpc.RpcError:
            # the chaos contract is a process DEATH mid-step, not a
            # catchable exception: survivors must recover from silence
            import signal
            os.kill(os.getpid(), signal.SIGKILL)

    # ------------------------------------------------------------- compute
    def forward(self, step, mb, x, target=None):
        self._chaos()
        return self._rt.forward(step, mb, x, target)

    def backward(self, step, mb, gy=None):
        self._chaos()
        return self._rt.backward(step, mb, gy)

    def apply_step(self, step):
        return self._rt.apply_step(step)

    def checkpoint(self, step):
        snap = self._rt.checkpoint(step)
        if self._storage_dir():
            self._write_storage_shard(snap)
        return snap

    def _storage_dir(self):
        return getattr(self, "_storage_path", None)

    def set_storage_path(self, path: Optional[str]):
        self._storage_path = path
        return True

    def _write_storage_shard(self, snap):
        """Durable shard for the restore_and_broadcast ladder: written
        best-effort at each boundary (recovery falls back to it only
        when the object-store snapshot ref is unreachable)."""
        try:
            from ray_tpu.train.sharded_checkpoint import save_stage_shard
            save_stage_shard(self._storage_path, self._rt.stage_idx, snap)
        except Exception:
            import logging
            logging.getLogger(__name__).warning(
                "stage %d: storage shard write failed",
                self._rt.stage_idx, exc_info=True)

    # ------------------------------------------------------------- control
    def abort_step(self, step):
        return self._rt.abort_step(step)

    def rollback(self):
        return self._rt.rollback()

    def compile_counts(self):
        return self._rt.compile_counts()

    def state_digest(self):
        return self._rt.state_digest()

    def ping(self):
        return True

    def preempting(self):
        return self._preempting

    def stop(self):
        self._stop.set()
        return True


# control methods answer while compute is queued: tag the group on the
# plain functions (actor.py reads __concurrency_group__ through
# ray_tpu.remote(), same as @ray_tpu.method(concurrency_group=...))
for _name in ("abort_step", "rollback", "compile_counts", "state_digest",
              "ping", "preempting", "stop", "set_storage_path"):
    getattr(PipelineStageActor, _name).__concurrency_group__ = "control"
del _name


class ActorStageHandle:
    """Controller-side wrapper around a PipelineStageActor: methods
    return ObjectRefs (activations/grads stay in the object store — the
    controller passes refs between stages, never bytes)."""

    remote = True

    def __init__(self, stage_idx: int, actor):
        self.stage_idx = stage_idx
        self.actor = actor

    @classmethod
    def provision(cls, stage_idx: int, n_stages: int, n_microbatches: int,
                  builder: Callable, snapshot=None,
                  preempt_marker: Optional[str] = None,
                  resources: Optional[Dict[str, float]] = None,
                  storage_path: Optional[str] = None) -> "ActorStageHandle":
        import ray_tpu
        opts: Dict[str, Any] = {
            "max_concurrency": 4,
            "concurrency_groups": {"control": 2},
        }
        if resources:
            opts["resources"] = dict(resources)
        actor = ray_tpu.remote(PipelineStageActor).options(**opts).remote(
            stage_idx, n_stages, n_microbatches, builder, snapshot,
            preempt_marker)
        h = cls(stage_idx, actor)
        if storage_path:
            h.fetch(actor.set_storage_path.remote(storage_path),
                    timeout=60.0)
        return h

    def forward(self, step, mb, x, target=None):
        return self.actor.forward.remote(step, mb, x, target)

    def backward(self, step, mb, gy=None):
        return self.actor.backward.remote(step, mb, gy)

    def apply_step(self, step):
        return self.actor.apply_step.remote(step)

    def abort_step(self, step):
        return self.actor.abort_step.remote(step)

    def checkpoint(self, step):
        return self.actor.checkpoint.remote(step)

    def rollback(self):
        return self.actor.rollback.remote()

    def compile_counts(self):
        return self.actor.compile_counts.remote()

    def state_digest(self):
        return self.actor.state_digest.remote()

    def ping(self, timeout: Optional[float] = 5.0) -> bool:
        import ray_tpu
        try:
            ray_tpu.get(self.actor.ping.remote(), timeout=timeout)
            return True
        except Exception:
            return False

    def preempting(self) -> bool:
        import ray_tpu
        try:
            return bool(ray_tpu.get(self.actor.preempting.remote(),
                                    timeout=5.0))
        except Exception:
            return False

    def kill(self):
        import ray_tpu
        try:
            ray_tpu.kill(self.actor)
        except Exception:
            pass

    def fetch(self, ref, timeout: Optional[float] = None):
        import ray_tpu
        return ray_tpu.get(ref, timeout=timeout)


# -------------------------------------------------------------- controller

class MPMDPipelineTrainer:
    """Drives an S-stage MPMD pipeline over stage handles: ref-chained
    schedule dispatch, step-boundary shard checkpoints, and the
    stage-loss lifecycle (park → re-provision → restore → replay →
    rejoin).

    stage_builders: one callable per stage returning its
        :class:`StageDefinition` (runs inside the stage's host process).
    remote=True provisions a :class:`PipelineStageActor` gang (one
        actor per stage, ``stage_resources[s]`` pinning each to its
        slice); remote=False runs stages in-process (tests/probe).
    provision_fn(stage_idx, snapshot) overrides stage provisioning
        entirely (tests inject failing handles through this)."""

    def __init__(self, stage_builders: List[Callable],
                 config: Optional[MPMDConfig] = None,
                 failure_config: Optional[FailureConfig] = None,
                 *, remote: bool = False,
                 stage_resources: Optional[List[Dict[str, float]]] = None,
                 provision_fn: Optional[Callable] = None,
                 marker_dir: Optional[str] = None):
        if len(stage_builders) < 2:
            raise ValueError("an MPMD pipeline needs >= 2 stages")
        self.builders = list(stage_builders)
        self.n_stages = len(self.builders)
        self.config = (config or MPMDConfig()).resolved()
        self.failure_config = failure_config or FailureConfig(
            max_failures=3, restart_policy="stage")
        self.remote = remote
        self.stage_resources = stage_resources or [None] * self.n_stages
        self._provision_fn = provision_fn
        self.schedule = make_schedule(self.config.schedule, self.n_stages,
                                      self.config.n_microbatches)
        self.replay = MicrobatchReplayBuffer(self.config.replay_depth)
        self.handles: List[Any] = []
        self._snap_refs: Dict[int, Any] = {}   # stage -> snapshot ref/tree
        self._ckpt_step = 0
        self._failures_left = self.failure_config.max_failures
        self.recoveries: List[Dict[str, Any]] = []
        self.history: List[Dict[str, Any]] = []
        self._marker_dir = marker_dir
        self._markers: List[Optional[str]] = [None] * self.n_stages
        if marker_dir:
            os.makedirs(marker_dir, exist_ok=True)
            self._markers = [os.path.join(marker_dir, f"stage_{s}.preempt")
                             for s in range(self.n_stages)]

    # ---------------------------------------------------------- provision
    def _provision(self, stage_idx: int, snapshot=None):
        if self._provision_fn is not None:
            return self._provision_fn(stage_idx, snapshot)
        return self._default_provision(stage_idx, snapshot)

    def _default_provision(self, stage_idx: int, snapshot=None):
        """The built-in stage host factory; provision_fn overrides can
        delegate here (it never re-enters the override)."""
        if self.remote:
            return ActorStageHandle.provision(
                stage_idx, self.n_stages, self.config.n_microbatches,
                self.builders[stage_idx], snapshot,
                preempt_marker=self._markers[stage_idx],
                resources=self.stage_resources[stage_idx],
                storage_path=self.config.storage_path)
        return LocalStageHandle(
            stage_idx, self.n_stages, self.config.n_microbatches,
            self.builders[stage_idx], snapshot,
            preempt_marker=self._markers[stage_idx])

    def start(self):
        """Provision the stage gang and take the step-0 checkpoint (so a
        loss before the first boundary can still restore)."""
        if self.handles:
            return self
        self.handles = [self._provision(s) for s in range(self.n_stages)]
        self._checkpoint_all(0)
        return self

    def preempt_marker(self, stage_idx: int) -> Optional[str]:
        """The per-stage notice-file path (chaos/StageKiller channel)."""
        return self._markers[stage_idx]

    # -------------------------------------------------------------- fit
    def fit(self, data_fn: Callable[[int], tuple], n_steps: int
            ) -> Dict[str, Any]:
        """Run ``n_steps`` pipeline steps. ``data_fn(step)`` returns
        (inputs, targets): M first-stage input microbatches and M
        last-stage target microbatches. Returns the run summary."""
        from ray_tpu._private import events
        self.start()
        with events.record_span("train.mpmd.fit", category="train",
                                n_stages=self.n_stages,
                                n_microbatches=self.config.n_microbatches,
                                schedule=self.config.schedule):
            step = 0
            while step < n_steps:
                step += 1
                inputs, targets = data_fn(step)
                self._check_shapes(inputs, targets)
                self.replay.record(step, inputs, targets)
                self._run_step_with_recovery(step, inputs, targets)
                if step % self.config.checkpoint_every == 0:
                    self._checkpoint_all(step)
                self._migrate_preempting(step)
        return self.summary()

    def _check_shapes(self, inputs, targets):
        M = self.config.n_microbatches
        if len(inputs) != M or len(targets) != M:
            raise ValueError(
                f"data_fn must return {M} input + {M} target microbatches "
                f"(got {len(inputs)}/{len(targets)})")

    def summary(self) -> Dict[str, Any]:
        last = self.history[-1] if self.history else {}
        return {"steps": len({h["step"] for h in self.history}),
                "last_metrics": last,
                "history": self.history,
                "recoveries": self.recoveries,
                "schedule": self.config.schedule,
                "bubble_fraction_analytic": pipeline_bubble_fraction(
                    self.n_stages, self.config.n_microbatches),
                "peak_live_activations": [
                    peak_live_activations(ops) for ops in self.schedule]}

    # ------------------------------------------------------ step execution
    def _run_step_with_recovery(self, step, inputs, targets):
        """Run one step; on stage loss, recover (park → replace →
        rollback) and replay the buffer — a loss DURING replay loops
        back into recovery against the same budget, so repeated chaos
        converges or degrades deterministically."""
        try:
            self._run_step(step, inputs, targets)
            return
        except StageLostError as e:
            lost, cause = e.stages, e.cause
        while True:
            t_rec = time.perf_counter()
            boundary = self._prepare_recovery(step, lost, cause)
            try:
                replayed = self.replay.replayable_from(boundary)
                for t in replayed:
                    ins, tgts = self.replay.get(t)
                    self._run_step(t, ins, tgts)
            except StageLostError as e:
                lost, cause = e.stages, e.cause
                continue
            self._note_recovery(step, lost, cause, boundary, replayed,
                                time.perf_counter() - t_rec)
            return

    def _run_step(self, step, inputs, targets):
        """Dispatch one step's full schedule ref-chained, then collect
        the per-stage apply barrier."""
        from ray_tpu._private import events
        t0 = time.perf_counter()
        apply_futs = self._dispatch(step, inputs, targets)
        metrics = self._collect_applies(step, apply_futs)
        wall = time.perf_counter() - t0
        row: Dict[str, Any] = {"step": step, "wall_s": round(wall, 6)}
        for m in metrics:
            s = m["stage"]
            row[f"stage{s}_compute_s"] = m["compute_s"]
            row[f"stage{s}_bubble_fraction"] = round(
                max(0.0, 1.0 - m["compute_s"] / wall), 4) if wall else 0.0
            if "loss" in m:
                row["loss"] = m["loss"]
        self.history.append(row)
        events.record_instant(
            "train.mpmd.step", category="train", step=step,
            wall_ms=round(wall * 1e3, 3),
            **({"loss": row["loss"]} if "loss" in row else {}))
        return row

    def _dispatch(self, step, inputs, targets):
        S = self.n_stages
        queues = [list(ops) for ops in self.schedule]
        fwd_out: Dict[tuple, Any] = {}
        bwd_out: Dict[tuple, Any] = {}
        while any(queues):
            progressed = False
            for s in range(S):
                while queues[s]:
                    op, mb = queues[s][0]
                    if op == OP_FWD:
                        if s == 0:
                            x = inputs[mb]
                        elif (s - 1, mb) in fwd_out:
                            x = fwd_out[(s - 1, mb)]
                        else:
                            break
                        tgt = targets[mb] if s == S - 1 else None
                        fwd_out[(s, mb)] = self.handles[s].forward(
                            step, mb, x, tgt)
                    else:
                        if s < S - 1 and (s + 1, mb) not in bwd_out:
                            break
                        gy = bwd_out[(s + 1, mb)] if s < S - 1 else None
                        bwd_out[(s, mb)] = self.handles[s].backward(
                            step, mb, gy)
                    queues[s].pop(0)
                    progressed = True
            if not progressed:
                raise ValueError("pipeline schedule deadlocked in dispatch")
        return [h.apply_step(step) for h in self.handles]

    def _collect_applies(self, step, apply_futs):
        metrics, first_err = [], None
        for s, fut in enumerate(apply_futs):
            try:
                metrics.append(self.handles[s].fetch(
                    fut, timeout=self.config.step_timeout_s))
            except Exception as e:
                if first_err is None:
                    first_err = (s, e)
        if first_err is not None:
            lost = [s for s, h in enumerate(self.handles)
                    if not h.ping(timeout=5.0)]
            raise StageLostError(
                lost[0] if lost else first_err[0],
                f"{type(first_err[1]).__name__}: {first_err[1]}",
                stages=lost or [first_err[0]])
        return metrics

    # ------------------------------------------------------- checkpointing
    def _checkpoint_all(self, step):
        futs = [h.checkpoint(step) for h in self.handles]
        for s, fut in enumerate(futs):
            if self.handles[s].remote:
                # keep the REF: the snapshot object stays in the arena
                # (cross-node restores ride the data plane); fetching it
                # to the controller would defeat the zero-copy path
                self._snap_refs[s] = fut
                # surface checkpoint errors without materializing: a
                # ping after submission is enough — the fetch happens
                # only on restore
            else:
                self._snap_refs[s] = self.handles[s].fetch(fut)
        self._ckpt_step = step

    def _restore_source(self, stage_idx: int):
        """Recovery ladder for a replacement stage's shard: object-store
        snapshot ref first; durable storage shard (one host reads, the
        weight plane fans out — sharded_checkpoint.restore_and_broadcast)
        when the ref is gone."""
        snap = self._snap_refs.get(stage_idx)
        if snap is not None and self.handles and \
                self.handles[stage_idx].remote:
            try:
                # probe the ref is still materializable (the dead
                # stage's node may have taken it down with it)
                import ray_tpu
                ready, _ = ray_tpu.wait([snap], num_returns=1, timeout=5.0)
                if not ready:
                    snap = None
            except Exception:
                snap = None
        if snap is not None:
            return snap
        if self.config.storage_path:
            from ray_tpu.train.sharded_checkpoint import (
                restore_stage_shard)
            return restore_stage_shard(self.config.storage_path, stage_idx,
                                       broadcast=self.remote)
        raise PipelineDegradedError(
            f"no restore source for stage {stage_idx} (snapshot ref lost "
            "and no storage_path configured)")

    # ------------------------------------------------------------ recovery
    def _prepare_recovery(self, step, lost: List[int], cause: str = ""
                          ) -> int:
        """Budget check → park survivors at the bounded barrier →
        re-provision lost stages from their shards → roll survivors back
        to the checkpoint boundary. Returns the boundary step the replay
        must start after. Raises PipelineDegradedError when stage-level
        recovery cannot proceed (policy/budget/barrier)."""
        from ray_tpu._private import events
        policy = getattr(self.failure_config, "restart_policy", "job")
        if policy != "stage":
            raise PipelineDegradedError(
                f"stage {lost} lost at step {step} and "
                f"restart_policy={policy!r}: job-level restart required")
        if self._failures_left <= 0:
            raise PipelineDegradedError(
                f"stage {lost} lost at step {step}: failure budget "
                f"exhausted (max_failures="
                f"{self.failure_config.max_failures})")
        self._failures_left -= 1
        events.record_instant(
            "train.mpmd.stage_lost", category="train", step=step,
            stages=",".join(map(str, lost)), cause=cause[:200])
        time.sleep(getattr(self.failure_config, "restart_backoff_s", 0.0)
                   or 0.0)

        # 1. park survivors at the bounded-deadline barrier
        survivors = [s for s in range(self.n_stages) if s not in lost]
        deadline = time.monotonic() + self.config.barrier_deadline_s
        barrier = [(s, self.handles[s].abort_step(step)) for s in survivors]
        stragglers = []
        for s, fut in barrier:
            left = deadline - time.monotonic()
            try:
                self.handles[s].fetch(fut, timeout=max(0.1, left))
            except Exception:
                stragglers.append(s)
        if stragglers:
            raise PipelineDegradedError(
                f"survivors {stragglers} missed the "
                f"{self.config.barrier_deadline_s}s park barrier after "
                f"stage {lost} loss — degrading to job-level restart")

        # 2. re-provision lost stages from their shard checkpoints
        for s in lost:
            try:
                self.handles[s].kill()
            except Exception:
                pass   # rtlint: disable=RT004 — corpse may be gone
            self.handles[s] = self._provision(s, self._restore_source(s))

        # 3. roll surviving stages back to the checkpoint boundary
        boundary = self._ckpt_step
        roll = [(s, self.handles[s].rollback()) for s in survivors]
        for s, fut in roll:
            got = self.handles[s].fetch(fut, timeout=60.0)
            if got != boundary:
                raise PipelineDegradedError(
                    f"stage {s} rolled back to step {got}, controller "
                    f"checkpoint boundary is {boundary}")
        return boundary

    def _note_recovery(self, step, lost, cause, boundary, replayed,
                       recovery_s):
        from ray_tpu._private import events
        self.recoveries.append({
            "step": step, "stages": list(lost), "cause": cause,
            "boundary": boundary, "replayed_steps": list(replayed),
            "steps_lost": len(replayed),
            "recovery_s": round(recovery_s, 3)})
        events.record_instant(
            "train.mpmd.stage_rejoined", category="train", step=step,
            stages=",".join(map(str, lost)), boundary=boundary,
            steps_replayed=len(replayed),
            recovery_ms=round(recovery_s * 1e3, 1))

    # --------------------------------------------------- graceful migration
    def _migrate_preempting(self, step):
        """Boundary-time migration for stages whose host got a
        preemption NOTICE (watch thread / marker file): fresh
        checkpoint, replacement provisioned from it, old actor reaped —
        zero replayed steps, optimizer state untouched."""
        preempting = []
        for s, h in enumerate(self.handles):
            try:
                if h.preempting():
                    preempting.append(s)
            except Exception:
                continue
        if not preempting:
            return
        from ray_tpu._private import events
        self._checkpoint_all(step)
        for s in preempting:
            old = self.handles[s]
            self.handles[s] = self._provision(s, self._snap_refs[s])
            try:
                old.kill()
            except Exception:
                pass   # rtlint: disable=RT004 — host is going away anyway
            if self._markers[s]:
                try:
                    os.remove(self._markers[s])
                except FileNotFoundError:
                    pass
            events.record_instant(
                "train.mpmd.stage_migrated", category="train", step=step,
                stage=s)

    # ------------------------------------------------------------- queries
    def compile_counts(self) -> List[Dict[str, int]]:
        futs = [h.compile_counts() for h in self.handles]
        return [self.handles[s].fetch(f, timeout=30.0)
                for s, f in enumerate(futs)]

    def state_digests(self) -> List[str]:
        futs = [h.state_digest() for h in self.handles]
        return [self.handles[s].fetch(f, timeout=60.0)
                for s, f in enumerate(futs)]

    def shutdown(self):
        for h in self.handles:
            try:
                if h.remote:
                    h.fetch(h.actor.stop.remote(), timeout=5.0)
                h.kill()
            except Exception:
                pass   # rtlint: disable=RT004 — teardown best-effort
        self.handles = []
