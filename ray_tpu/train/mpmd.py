"""Elastic MPMD pipeline training: per-stage programs on per-stage
meshes, activations over the data plane, stage-level preemption recovery.

The SPMD pipeline (parallel/pipeline.py) keeps every stage inside ONE
jitted program on one mesh — the right shape inside a slice, but it
cannot span slices (no ICI) and a single lost host kills the whole
program. This module is the cross-slice shape from the MPMD pipeline
paper (arXiv 2412.14374): each stage is its OWN program compiled once on
its OWN mesh/slice, hosted by an actor; activations and input-gradients
hop stage-to-stage as object-store objects — created in the pinned
shared-memory arena by the producing actor and, across nodes, shipped by
the PR 5 zero-copy binary data plane (the controller only routes refs,
bytes never visit it). The microbatch schedule (1F1B by default, GPipe
optional — parallel/pipeline.py schedule_*) is dispatched ref-chained:
every op of a step is submitted up front and the per-actor ordered
queues + object dependencies realize the pipeline without a host round
trip per hop.

Stage loss is a first-class lifecycle, mirroring PR 9's serving shape:

  notice   — each stage actor watches ``tpu.check_preemption_notice()``
             (plus its per-stage marker file, the chaos channel); a
             preempting stage is migrated at the NEXT step boundary:
             fresh shard checkpoint, replacement provisioned, old actor
             reaped — zero steps replayed.
  crash    — a stage actor that dies mid-step (preemption without
             notice, chaos ``StageKiller``) surfaces as failed applies /
             dead pings. Surviving stages PARK at a bounded-deadline
             barrier (abort the in-flight step, roll back to the last
             checkpoint boundary — their params never left the process);
             the controller re-provisions the stage from its shard
             checkpoint (object-store snapshot ref first; storage shard
             via ``sharded_checkpoint.restore_and_broadcast`` when a
             ``storage_path`` is configured and the ref is gone), then
             REPLAYS the buffered input microbatches. Replay re-runs the
             identical per-stage op order through the identical
             compiled-once programs, so post-replay optimizer state is
             bit-identical to an uninterrupted run; training resumes
             within ``replay_depth + 1`` steps of where it stopped.
  degrade  — a survivor that misses the park barrier
             (``mpmd_barrier_deadline_s``) or an exhausted
             ``FailureConfig.max_failures`` budget raises
             :class:`PipelineDegradedError`; the job-level
             ``restart_policy="job"`` ladder (trainer.py) takes over.

Compile-once discipline (the engine's ``decode`` rule applied to
training): each stage jits exactly one forward, one backward, one
grad-accumulate and one optimizer-apply program PER VIRTUAL CHUNK for
its life; the counters are asserted ==1 across recovery — survivors
never retrace and a replacement compiles each program exactly once in
its fresh process. The programs are AOT lowered+compiled (the
``StepProfiler.wrap_jit`` shape), so the XLA cost analysis feeds MFU
attribution for free, and the grad-accumulate/apply programs donate
their optimizer+param input buffers (rebound immediately after the
call; snapshots deep-copy for exactly this reason).

Step-time physics (ROADMAP item 5, the MFU attack):

  interleaved schedules — ``MPMDConfig.virtual_stages = v`` hosts v
      virtual chunks per stage actor (virtual stage vs = chunk*S + s),
      cutting the flush bubble from (S-1)/(M+S-1) toward
      (S-1)/(v*M+S-1); dispatch ref-chains the virtual-chunk dependency
      graph and per-chunk backward order stays microbatch-FIFO, so
      recovery replay and grad accumulation are bit-identical to the
      plain pipeline over the same V virtual stages.
  stage gangs — :class:`GangStageHandle` makes one stage a gang of
      workers over one multi-host mesh (the Podracer shape, slice
      acquisition folded in from ``backend_executor``): gang-consistent
      dispatch, activations enter/leave via rank 0's arena, digests
      gathered and compared across ranks, lifecycle unchanged.
  off-step I/O — step-boundary checkpoints snapshot to host on a
      background thread (``checkpoint_begin``/``checkpoint_result``)
      and durable shards seal/put through an ``AsyncShardWriter``;
      the only barriers are at recovery (rollback) and before the next
      donating apply. ``StepProfiler`` ("mpmd") attributes each step's
      compute/host-gap/data-wait and per-stage bubble as
      ``runtime_mpmd_*`` gauges and timeline spans.

Unit-tier shape: the controller talks to stages through a handle
protocol; :class:`LocalStageHandle` runs stages in-process (tests,
probes, the MULTICHIP dryrun with per-stage device subsets) while
:class:`ActorStageHandle` wraps a :class:`PipelineStageActor` gang —
same dispatcher, same recovery path.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu._private.config import cfg
from ray_tpu.parallel.pipeline import (OP_BWD, OP_FWD, make_schedule,
                                       op_chunk, peak_live_activations,
                                       pipeline_bubble_fraction)
from ray_tpu.train.config import FailureConfig


class StageLostError(RuntimeError):
    """One or more stage actors died or failed mid-step; carries the
    lost stage indexes (a single chaos event can take several stages —
    e.g. a node death under two colocated stages)."""

    def __init__(self, stage_idx: int, cause: str = "",
                 stages: Optional[List[int]] = None):
        self.stages = sorted(set(stages or [stage_idx]))
        super().__init__(f"pipeline stage(s) {self.stages} lost"
                         + (f": {cause}" if cause else ""))
        self.stage_idx = stage_idx
        self.cause = cause


class PipelineDegradedError(RuntimeError):
    """Stage-level recovery could not proceed (park-barrier deadline
    missed or failure budget exhausted); the pipeline is parked and the
    caller must fall back to a job-level restart."""


@dataclasses.dataclass
class StageDefinition:
    """What one pipeline stage computes. Built INSIDE the stage's
    process by the per-stage builder so params land on the stage's own
    mesh/devices.

    stage_fn(params, x) -> y; the last stage's ``loss_fn(y, targets)``
    -> scalar closes the pipeline. ``place`` re-places a restored host
    (numpy) state tree onto the stage's devices/shardings (defaults to
    leaving host arrays for jit to commit)."""
    stage_fn: Callable[[Any, Any], Any]
    params: Any
    optimizer: Any                                  # optax gradient xform
    loss_fn: Optional[Callable[[Any, Any], Any]] = None
    place: Optional[Callable[[Any], Any]] = None


@dataclasses.dataclass
class MPMDConfig:
    """Pipeline-shape + elasticity knobs (defaults from the flag
    registry, overridable per trainer)."""
    n_microbatches: int = 4
    schedule: str = "1f1b"                  # "1f1b" | "gpipe"
    virtual_stages: int = 1                 # v chunks per stage (1f1b only)
    replay_depth: Optional[int] = None      # cfg.mpmd_replay_depth
    checkpoint_every: Optional[int] = None  # default: replay_depth
    barrier_deadline_s: Optional[float] = None
    step_timeout_s: Optional[float] = None
    storage_path: Optional[str] = None      # durable shard checkpoints
    async_checkpoint: bool = True           # snapshot/seal off the hot path
    donate_buffers: bool = True             # donate opt+param apply inputs
    step_profile: bool = True               # runtime_mpmd_* attribution

    def resolved(self) -> "MPMDConfig":
        c = dataclasses.replace(self)
        if c.replay_depth is None:
            c.replay_depth = cfg.mpmd_replay_depth
        if c.checkpoint_every is None:
            c.checkpoint_every = c.replay_depth
        if c.barrier_deadline_s is None:
            c.barrier_deadline_s = cfg.mpmd_barrier_deadline_s
        if c.step_timeout_s is None:
            c.step_timeout_s = cfg.mpmd_step_timeout_s
        if c.n_microbatches < 1:
            raise ValueError("n_microbatches must be >= 1")
        if c.virtual_stages < 1:
            raise ValueError("virtual_stages must be >= 1")
        if c.virtual_stages > 1 and c.schedule != "1f1b":
            raise ValueError(
                "interleaved virtual stages require the '1f1b' schedule")
        if c.replay_depth < 1:
            raise ValueError("replay_depth must be >= 1")
        if c.checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        if c.checkpoint_every > c.replay_depth:
            raise ValueError(
                f"checkpoint_every={c.checkpoint_every} must be <= "
                f"replay_depth={c.replay_depth}: the replay buffer must "
                "cover every step since the last shard checkpoint")
        return c


# ------------------------------------------------------------ replay buffer

class MicrobatchReplayBuffer:
    """Bounded per-step retention of input microbatches (+ targets) so a
    re-provisioned stage can replay every step since the last shard
    checkpoint. Eviction is deterministic: strictly oldest-first once
    more than ``depth`` steps are held. Stored arrays are snapshotted
    (np.asarray copies) so later caller mutation can't corrupt replay.

    Sizing is accounted against the CORRECTED per-stage live-buffer
    peak (``peak_live_activations`` with grad-accumulation buffers
    included): the pipeline's worst-case microbatch-sized memory is the
    replay window (depth * M input microbatches held here) PLUS the
    busiest stage's in-flight stashes and grad buffers —
    ``budget()`` reports both so the controller sizes from the real
    number, not the activation-only undercount."""

    def __init__(self, depth: int, *, n_microbatches: Optional[int] = None,
                 peak_live_buffers: Optional[List[int]] = None):
        if depth < 1:
            raise ValueError("replay depth must be >= 1")
        self.depth = depth
        self.n_microbatches = n_microbatches
        self.peak_live_buffers = list(peak_live_buffers) \
            if peak_live_buffers is not None else None
        self._steps: Dict[int, Any] = {}

    def record(self, step: int, inputs: List[Any], targets: List[Any]):
        self._steps[step] = (
            [np.array(np.asarray(x)) for x in inputs],
            [np.array(np.asarray(t)) for t in targets])
        while len(self._steps) > self.depth:
            del self._steps[min(self._steps)]

    def budget(self) -> Dict[str, Any]:
        """Memory accounting for the replay window: bytes actually held
        plus the microbatch-buffer peak the pipeline adds on top."""
        held = sum(a.nbytes for ins, tgts in self._steps.values()
                   for a in (*ins, *tgts))
        out: Dict[str, Any] = {"depth": self.depth,
                               "steps_held": len(self._steps),
                               "bytes_held": int(held)}
        if self.n_microbatches is not None:
            out["replay_microbatches"] = self.depth * self.n_microbatches
            if self.peak_live_buffers:
                out["peak_live_stage_buffers"] = max(self.peak_live_buffers)
                out["peak_microbatch_buffers"] = (
                    out["replay_microbatches"]
                    + out["peak_live_stage_buffers"])
        return out

    def steps(self) -> List[int]:
        return sorted(self._steps)

    def get(self, step: int):
        if step not in self._steps:
            raise KeyError(
                f"step {step} not in replay buffer (held: {self.steps()}, "
                f"depth {self.depth})")
        return self._steps[step]

    def replayable_from(self, boundary_step: int) -> List[int]:
        """Steps after ``boundary_step`` available for replay, in order;
        raises if a gap means the boundary is too old to recover from."""
        want = [s for s in self.steps() if s > boundary_step]
        expect = list(range(boundary_step + 1, boundary_step + 1 + len(want)))
        if want != expect:
            raise KeyError(
                f"replay gap: checkpoint at step {boundary_step} but "
                f"buffer holds {self.steps()}")
        return want


# ------------------------------------------------------------ stage runtime

class _AotProgram:
    """Compile-once AOT wrapper around one jitted stage program (the
    ``StepProfiler.wrap_jit`` shape, instance-scoped): the first call
    per input shape traces/lowers/compiles exactly once — the
    trace-time compile counters fire there and only there — and later
    calls run the compiled executable directly, so there is no retrace
    surface at all. The XLA cost analysis is kept (``flops``/
    ``bytes_accessed``) for the trainer's MFU attribution. Backends
    that reject AOT fall back to the plain jitted callable (cost stays
    0, behavior identical)."""

    __slots__ = ("_jitted", "_cache", "flops", "bytes_accessed")

    def __init__(self, jitted):
        self._jitted = jitted
        self._cache: Dict[tuple, Any] = {}
        self.flops = 0.0
        self.bytes_accessed = 0.0

    def __call__(self, *args):
        from ray_tpu.util.profiling import _shape_key, cost_of_compiled
        try:
            key = _shape_key(args)
        except Exception:
            return self._jitted(*args)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._jitted
            try:
                import warnings
                with warnings.catch_warnings():
                    # donation is opportunistic: backends without buffer
                    # aliasing (CPU) ignore it, which is fine — silence
                    # the per-trace nag, the audit runs on TPU numbers
                    warnings.filterwarnings(
                        "ignore",
                        message="Some donated buffers were not usable")
                    compiled = self._jitted.lower(*args).compile()
                cost = cost_of_compiled(compiled)
                self.flops = cost["flops"]
                self.bytes_accessed = cost["bytes_accessed"]
                fn = compiled
            except Exception:
                pass   # rtlint: disable=RT004 — plain jit fallback below
            self._cache[key] = fn
        try:
            return fn(*args)
        except Exception:
            if fn is self._jitted:
                raise
            # a strict AOT executable rejected this input (e.g. an
            # uncommitted sharding): pin the fallback for this shape
            self._cache[key] = self._jitted
            return self._jitted(*args)


class StageRuntime:
    """One stage's compute engine: compile-once fwd/bwd/accumulate/apply
    programs over the StageDefinition, saved-input bookkeeping for the
    recompute-style backward, grad accumulation in schedule order (replay
    determinism), and host-snapshot checkpoint/rollback. Runs unchanged
    inside a :class:`PipelineStageActor` or a :class:`LocalStageHandle`;
    under interleaved schedules a host holds one StageRuntime per
    virtual chunk, each with ``stage_idx`` = its VIRTUAL stage index.

    With ``donate=True`` the grad-accumulate program donates the old
    accumulator and the apply program donates params/opt_state/grads —
    all rebound immediately, so the only aliasing hazard is a host
    snapshot taken as a VIEW of a later-donated buffer; snapshots
    therefore always deep-copy (the donation-audit invariant the RT002
    lint rule guards statically).

    Checkpointing is asynchronous: ``checkpoint_begin`` captures the
    immutable param/opt_state trees and returns; a background thread
    materializes the host copy. ``checkpoint_result``/``rollback``/the
    next donating ``apply_step`` are the barrier points."""

    def __init__(self, defn: StageDefinition, *, stage_idx: int,
                 n_stages: int, n_microbatches: int, donate: bool = True):
        import jax

        self.defn = defn
        self.stage_idx = stage_idx
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.donate = donate
        self.is_first = stage_idx == 0
        self.is_last = stage_idx == n_stages - 1
        if self.is_last and defn.loss_fn is None:
            raise ValueError("last stage needs a loss_fn")
        self.step = 0
        self.params = defn.params
        self.opt_state = defn.optimizer.init(defn.params)
        self.fwd_compile_count = 0
        self.bwd_compile_count = 0
        self.apply_compile_count = 0
        self._saved: Dict[tuple, Any] = {}
        self._gacc = None
        self._losses: List[Any] = []
        self._compute_s = 0.0
        self._op_s: Dict[str, float] = {}
        self._op_n: Dict[str, int] = {}
        self._ckpt_lock = threading.Lock()
        self._ckpt_thread: Optional[threading.Thread] = None
        self._ckpt_err: Optional[BaseException] = None
        self._last_snapshot = self._host_snapshot()

        stage_fn, loss_fn = defn.stage_fn, defn.loss_fn
        M = n_microbatches

        def fwd(params, x):
            self.fwd_compile_count += 1       # trace-time only
            return stage_fn(params, x)

        def fwd_last(params, x, target):
            self.fwd_compile_count += 1
            return loss_fn(stage_fn(params, x), target)

        def bwd(params, x, gy):
            self.bwd_compile_count += 1
            _y, vjp = jax.vjp(stage_fn, params, x)
            gp, gx = vjp(gy)
            return gx, gp

        def bwd_last(params, x, target):
            self.bwd_compile_count += 1
            loss, (gp, gx) = jax.value_and_grad(
                lambda p, xx: loss_fn(stage_fn(p, xx), target),
                argnums=(0, 1))(params, x)
            return gx, gp, loss

        def acc(a, b):
            return jax.tree.map(lambda u, v: u + v, a, b)

        def apply(params, opt_state, gacc):
            self.apply_compile_count += 1
            g = jax.tree.map(lambda u: u / M, gacc)
            updates, new_opt = defn.optimizer.update(g, opt_state,
                                                     params=params)
            import optax
            return optax.apply_updates(params, updates), new_opt

        # fwd/bwd inputs (params, activations) are reused across
        # microbatches — never donate those; the accumulator and the
        # optimizer/param buffers are consumed exactly once per call.
        donate_acc = {"donate_argnums": (0,)} if donate else {}
        donate_apply = {"donate_argnums": (0, 1, 2)} if donate else {}
        self._fwd_j = _AotProgram(jax.jit(fwd_last if self.is_last else fwd))
        self._bwd_j = _AotProgram(jax.jit(bwd_last if self.is_last else bwd))
        self._acc_j = _AotProgram(jax.jit(acc, **donate_acc))
        self._apply_j = _AotProgram(jax.jit(apply, **donate_apply))

    # ------------------------------------------------------------- compute
    def _timed(self, kind: str, fn, *args):
        import jax
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        self._compute_s += dt
        self._op_s[kind] = self._op_s.get(kind, 0.0) + dt
        self._op_n[kind] = self._op_n.get(kind, 0) + 1
        return out

    def flops_per_step(self) -> float:
        """One full step's FLOPs for this chunk from the compiled
        programs' cost analyses (0 until first execution / when the
        backend exposes no cost model)."""
        M = self.n_microbatches
        return (M * (self._fwd_j.flops + self._bwd_j.flops)
                + max(0, M - 1) * self._acc_j.flops
                + self._apply_j.flops)

    def forward(self, step: int, mb: int, x, target=None):
        """Run F(step, mb). Non-last stages return the activation (the
        object the next stage consumes); the last stage returns its
        per-microbatch loss. The input is saved for the recompute-style
        backward and dropped by it (or by abort_step). Outputs cross a
        MESH boundary, so they leave as host arrays — in-process that is
        the device→host hop the object-store hand-off pays anyway, and
        it keeps each stage's program free of the neighbor's placement."""
        if self.is_last:
            self._saved[(step, mb)] = (x, target)
            return np.asarray(self._timed("fwd", self._fwd_j, self.params,
                                          x, target))
        self._saved[(step, mb)] = x
        return np.asarray(self._timed("fwd", self._fwd_j, self.params, x))

    def backward(self, step: int, mb: int, gy=None):
        """Run B(step, mb): recompute-vjp over the saved input,
        accumulate param grads IN CALL ORDER (the schedule's order —
        replay hits the same order, hence bit-identical accumulation),
        return the input-gradient for the upstream stage (host array —
        it crosses the mesh boundary too)."""
        if self.is_last:
            x, target = self._saved.pop((step, mb))
            gx, gp, loss = self._timed("bwd", self._bwd_j, self.params, x,
                                       target)
            self._losses.append(np.asarray(loss))
        else:
            x = self._saved.pop((step, mb))
            gx, gp = self._timed("bwd", self._bwd_j, self.params, x, gy)
        self._gacc = gp if self._gacc is None \
            else self._timed("acc", self._acc_j, self._gacc, gp)
        return np.asarray(gx)

    def apply_step(self, step: int) -> Dict[str, Any]:
        """Step boundary: apply the accumulated (mean) gradient, clear
        per-step state, return stage metrics. Barriers any in-flight
        async snapshot first — apply DONATES the param/opt_state
        buffers, and the snapshot thread must not be copying them when
        their storage is reused."""
        if self._gacc is None:
            raise RuntimeError(f"stage {self.stage_idx}: apply_step({step}) "
                               "with no accumulated gradients")
        if self._saved:
            raise RuntimeError(
                f"stage {self.stage_idx}: {len(self._saved)} saved "
                f"activations outstanding at apply_step({step})")
        self._ckpt_barrier()
        self.params, self.opt_state = self._timed(
            "apply", self._apply_j, self.params, self.opt_state, self._gacc)
        metrics: Dict[str, Any] = {
            "step": step, "stage": self.stage_idx,
            "compute_s": round(self._compute_s, 6),
            "fwd_compile_count": self.fwd_compile_count,
            "bwd_compile_count": self.bwd_compile_count,
            "apply_compile_count": self.apply_compile_count,
            "flops": self.flops_per_step(),
        }
        for kind in ("fwd", "bwd"):
            metrics[f"{kind}_s"] = round(self._op_s.get(kind, 0.0), 6)
            metrics[f"{kind}_n"] = self._op_n.get(kind, 0)
        if self.is_last and self._losses:
            metrics["loss"] = float(np.mean([np.asarray(l)
                                             for l in self._losses]))
        self._gacc = None
        self._losses = []
        self._compute_s = 0.0
        self._op_s = {}
        self._op_n = {}
        self.step = step
        return metrics

    def abort_step(self, step: int) -> bool:
        """Park: drop the in-flight step's saved activations, partial
        grad accumulation and losses. Params/opt_state are untouched —
        they only move at apply_step."""
        self._saved = {k: v for k, v in self._saved.items()
                       if k[0] != step}
        self._gacc = None
        self._losses = []
        self._compute_s = 0.0
        self._op_s = {}
        self._op_n = {}
        return True

    # ------------------------------------------------------- checkpointing
    def _snapshot_of(self, step: int, params, opt_state) -> Dict[str, Any]:
        import jax
        # DEEP copies, not np.asarray views: a view would alias the very
        # device buffer the next apply_step DONATES, and XLA reusing the
        # storage would silently corrupt the snapshot (the
        # donated-buffer-reuse shape rtlint RT002 flags).
        def copy(a):
            return np.array(np.asarray(a))
        return {"step": step,
                "stage": self.stage_idx,
                "params": jax.tree.map(copy, params),
                "opt_state": jax.tree.map(copy, opt_state)}

    def _host_snapshot(self) -> Dict[str, Any]:
        return self._snapshot_of(self.step, self.params, self.opt_state)

    def _ckpt_barrier(self):
        """Join the in-flight async snapshot, surfacing its error."""
        t = self._ckpt_thread
        if t is not None:
            t.join()
            self._ckpt_thread = None
            if self._ckpt_err is not None:
                err, self._ckpt_err = self._ckpt_err, None
                raise RuntimeError(
                    f"stage {self.stage_idx}: async checkpoint "
                    "failed") from err

    def checkpoint_begin(self, step: int,
                         on_sealed: Optional[Callable] = None) -> bool:
        """Start a step-boundary shard snapshot OFF the hot path: the
        immutable param/opt_state trees are captured by reference (no
        copy on the caller's thread) and a background thread
        materializes the host copy — overlapping the next step's
        compute. ``on_sealed(snapshot)`` runs on that thread once the
        copy exists (the durable-shard writer hook)."""
        if step != self.step:
            raise RuntimeError(
                f"stage {self.stage_idx}: checkpoint({step}) at "
                f"step {self.step} — checkpoints are step-boundary only")
        self._ckpt_barrier()                  # one snapshot in flight max
        params, opt_state = self.params, self.opt_state

        def work():
            try:
                snap = self._snapshot_of(step, params, opt_state)
                with self._ckpt_lock:
                    self._last_snapshot = snap
                if on_sealed is not None:
                    on_sealed(snap)
            except BaseException as e:        # surfaced at the barrier
                self._ckpt_err = e

        self._ckpt_thread = threading.Thread(
            target=work, name=f"stage-{self.stage_idx}-ckpt", daemon=True)
        self._ckpt_thread.start()
        return True

    def checkpoint_result(self, step: int) -> Dict[str, Any]:
        """Barrier on the async snapshot and return it (the object the
        controller parks in the store for replacement stages)."""
        self._ckpt_barrier()
        with self._ckpt_lock:
            snap = self._last_snapshot
        if snap.get("step") != step:
            raise RuntimeError(
                f"stage {self.stage_idx}: checkpoint_result({step}) but "
                f"last snapshot is for step {snap.get('step')}")
        return snap

    def checkpoint(self, step: int) -> Dict[str, Any]:
        """Synchronous snapshot (begin + result) — the pre-async
        protocol, kept for callers that want the boundary cost inline."""
        self.checkpoint_begin(step)
        return self.checkpoint_result(step)

    def rollback(self) -> int:
        """Roll params/opt_state back to the last checkpoint boundary;
        returns the boundary step. Recovery is THE barrier point for
        async snapshots — an in-flight copy is joined first."""
        self._ckpt_barrier()
        with self._ckpt_lock:
            snap = self._last_snapshot
        self.load_snapshot(snap)
        return self.step

    def load_snapshot(self, snap: Dict[str, Any]):
        place = self.defn.place or (lambda t: t)
        self.params = place(snap["params"])
        self.opt_state = place(snap["opt_state"])
        self.step = int(snap["step"])
        self._last_snapshot = snap
        self._saved = {}
        self._gacc = None
        self._losses = []

    def state_digest(self) -> str:
        """sha256 over every params/opt_state leaf — the bit-identity
        probe the elastic tests compare against an uninterrupted run."""
        import jax
        h = hashlib.sha256()
        for tree in (self.params, self.opt_state):
            for leaf in jax.tree.leaves(tree):
                a = np.asarray(leaf)
                h.update(str(a.dtype).encode())
                h.update(str(a.shape).encode())
                h.update(a.tobytes())
        return h.hexdigest()

    def compile_counts(self) -> Dict[str, int]:
        return {"fwd": self.fwd_compile_count,
                "bwd": self.bwd_compile_count,
                "apply": self.apply_compile_count}


# ------------------------------------------------------------- stage hosts

def _build_definition(builder: Callable, stage_idx: int) -> StageDefinition:
    """Builders may take (stage_idx) or nothing."""
    try:
        import inspect
        takes_arg = len(inspect.signature(builder).parameters) >= 1
    except (TypeError, ValueError):
        takes_arg = True
    defn = builder(stage_idx) if takes_arg else builder()
    if not isinstance(defn, StageDefinition):
        raise TypeError(f"stage builder must return StageDefinition, "
                        f"got {type(defn)!r}")
    return defn


def _load_chunk_snapshots(rts: List[StageRuntime], snapshot):
    """Restore a host's runtimes from a snapshot: a single dict for the
    plain one-chunk host, a list (one per virtual chunk, chunk order)
    under interleaving."""
    snaps = [snapshot] if isinstance(snapshot, dict) else list(snapshot)
    if len(snaps) != len(rts):
        raise ValueError(
            f"snapshot has {len(snaps)} chunk shards, host has "
            f"{len(rts)} virtual chunks")
    for rt, snap in zip(rts, snaps):
        rt.load_snapshot(snap)


class _Now:
    """Pre-resolved 'future' for the in-process transport."""
    __slots__ = ("value", "error")

    def __init__(self, value=None, error: Optional[BaseException] = None):
        self.value = value
        self.error = error

    def result(self):
        if self.error is not None:
            raise self.error
        return self.value


class _Later:
    """Deferred 'future' for the in-process transport: the thunk runs
    on first fetch — how the local handles keep the async-checkpoint
    barrier OFF the hot path (the controller stores this unresolved
    and only resolves it on the recovery/restore path)."""
    __slots__ = ("_fn", "_done", "_value", "_error")

    def __init__(self, fn: Callable[[], Any]):
        self._fn = fn
        self._done = False
        self._value = None
        self._error: Optional[BaseException] = None

    def result(self):
        if not self._done:
            try:
                self._value = self._fn()
            except BaseException as e:
                self._error = e
            self._done = True
            self._fn = None
        if self._error is not None:
            raise self._error
        return self._value


class LocalStageHandle:
    """In-process stage host speaking the same protocol as the actor
    transport: every call returns a future (here pre-resolved), chaos
    injection runs at forward/backward entry (``stage_step`` spec — a
    fire marks the handle DEAD and every later call raises StageLostError,
    the in-process analog of a SIGKILLed actor), and ``preempting()``
    polls the per-stage marker file. ``fail_at=(step, op)`` arms a
    deterministic one-shot death for tests/probes."""

    remote = False

    def __init__(self, stage_idx: int, n_stages: int, n_microbatches: int,
                 builder: Optional[Callable] = None,
                 snapshot: Optional[Any] = None,
                 preempt_marker: Optional[str] = None,
                 fail_at: Optional[tuple] = None,
                 chunk_builders: Optional[List[tuple]] = None,
                 donate: bool = True):
        self.stage_idx = stage_idx
        if chunk_builders is None:
            chunk_builders = [(stage_idx, builder)]
        self._rts = [
            StageRuntime(_build_definition(b, vs), stage_idx=vs,
                         n_stages=n_stages, n_microbatches=n_microbatches,
                         donate=donate)
            for vs, b in chunk_builders]
        self._rt = self._rts[0]            # single-chunk back-compat alias
        if snapshot is not None:
            _load_chunk_snapshots(self._rts, snapshot)
        self._marker = preempt_marker
        self._fail_at = fail_at
        self._dead = False

    # ------------------------------------------------------ chaos plumbing
    def _chaos(self, step: int, op: str):
        if self._dead:
            raise StageLostError(self.stage_idx, "stage already dead")
        if self._fail_at is not None and self._fail_at == (step, op):
            self._fail_at = None
            self._dead = True
            raise StageLostError(self.stage_idx,
                                 f"armed failure at step {step} {op}")
        from ray_tpu._private import rpc
        try:
            rpc._maybe_inject_failure("stage_step")
        except rpc.RpcError as e:
            self._dead = True
            raise StageLostError(self.stage_idx, str(e)) from e

    def _call(self, fn, *args) -> _Now:
        try:
            return _Now(fn(*args))
        except BaseException as e:   # surfaced at fetch, like a ref
            return _Now(error=e)

    # ------------------------------------------------------------ protocol
    @staticmethod
    def _unwrap(v):
        # upstream outputs arrive as _Now futures; a poisoned one
        # re-raises the upstream loss here, mirroring how a failed
        # object-ref dependency fails the downstream actor task
        return v.result() if isinstance(v, _Now) else v

    def forward(self, step, mb, x, target=None, chunk=0) -> _Now:
        def run():
            self._chaos(step, OP_FWD)
            return self._rts[chunk].forward(step, mb, self._unwrap(x),
                                            target)
        return self._call(run)

    def backward(self, step, mb, gy=None, chunk=0) -> _Now:
        def run():
            self._chaos(step, OP_BWD)
            return self._rts[chunk].backward(step, mb, self._unwrap(gy))
        return self._call(run)

    def apply_step(self, step) -> _Now:
        def run():
            if self._dead:
                raise StageLostError(self.stage_idx, "stage already dead")
            return [rt.apply_step(step) for rt in self._rts]
        return self._call(run)

    def abort_step(self, step) -> _Now:
        if self._dead:
            return _Now(error=StageLostError(self.stage_idx, "dead"))
        return self._call(lambda: all([rt.abort_step(step)
                                       for rt in self._rts]))

    def checkpoint(self, step) -> _Now:
        if self._dead:
            return _Now(error=StageLostError(self.stage_idx, "dead"))
        return self._call(lambda: [rt.checkpoint(step) for rt in self._rts])

    def checkpoint_begin(self, step) -> _Now:
        if self._dead:
            return _Now(error=StageLostError(self.stage_idx, "dead"))
        return self._call(lambda: all([rt.checkpoint_begin(step)
                                       for rt in self._rts]))

    def checkpoint_result(self, step) -> _Later:
        # deferred: the barrier on the background snapshot happens at
        # fetch time (restore path), not on the training hot path
        return _Later(lambda: [rt.checkpoint_result(step)
                               for rt in self._rts])

    def rollback(self) -> _Now:
        if self._dead:
            return _Now(error=StageLostError(self.stage_idx, "dead"))

        def run():
            bounds = [rt.rollback() for rt in self._rts]
            if len(set(bounds)) != 1:
                raise RuntimeError(
                    f"stage {self.stage_idx}: virtual chunks rolled back "
                    f"to different boundaries {bounds}")
            return bounds[0]
        return self._call(run)

    def compile_counts(self) -> _Now:
        return self._call(lambda: [rt.compile_counts()
                                   for rt in self._rts])

    def state_digest(self) -> _Now:
        return self._call(lambda: [rt.state_digest() for rt in self._rts])

    def ping(self, timeout: Optional[float] = None) -> bool:
        return not self._dead

    def preempting(self) -> bool:
        if self._dead:
            return False
        if self._marker and os.path.exists(self._marker):
            return True
        from ray_tpu._private.accelerators.tpu import \
            check_preemption_notice
        return check_preemption_notice()

    def kill(self):
        self._dead = True

    def fetch(self, fut: _Now, timeout: Optional[float] = None):
        return fut.result()


class PipelineStageActor:
    """Actor hosting one pipeline stage pinned to its own mesh/slice.
    Compute methods ride the DEFAULT (ordered) concurrency group —
    dispatch order is execution order, which the replay-determinism
    guarantee leans on; control methods (ping/abort/rollback/...)
    declare the ``control`` group so the controller can park or probe a
    stage while compute is queued. Chaos: the ``stage_step`` injection
    SIGKILLs the process mid-step (``util.chaos.StageKiller``), the
    hardest death the recovery path must absorb."""

    def __init__(self, stage_idx: int, n_stages: int, n_microbatches: int,
                 builder: Optional[Callable] = None,
                 snapshot: Optional[Any] = None,
                 preempt_marker: Optional[str] = None,
                 chunk_builders: Optional[List[tuple]] = None,
                 donate: bool = True):
        if chunk_builders is None:
            chunk_builders = [(stage_idx, builder)]
        self._rts = [
            StageRuntime(_build_definition(b, vs), stage_idx=vs,
                         n_stages=n_stages, n_microbatches=n_microbatches,
                         donate=donate)
            for vs, b in chunk_builders]
        self._rt = self._rts[0]            # single-chunk back-compat alias
        if snapshot is not None:
            snapshot = self._materialize(snapshot)
            _load_chunk_snapshots(self._rts, snapshot)
        self._marker = preempt_marker
        self._preempting = False
        self._shard_writer = None
        self._stop = threading.Event()
        self._watch = threading.Thread(target=self._watch_loop,
                                       name=f"stage-{stage_idx}-watch",
                                       daemon=True)
        self._watch.start()

    @staticmethod
    def _materialize(snapshot):
        """Snapshots may arrive as object refs (broadcast restore) —
        per chunk or whole — depending on the restore ladder rung."""
        import ray_tpu

        def one(s):
            return s if s is None or isinstance(s, dict) else ray_tpu.get(s)
        if isinstance(snapshot, (list, tuple)):
            return [one(s) for s in snapshot]
        return one(snapshot)

    def _watch_loop(self):
        from ray_tpu._private.accelerators.tpu import \
            check_preemption_notice
        while not self._stop.is_set():
            try:
                if (self._marker and os.path.exists(self._marker)) \
                        or check_preemption_notice():
                    self._preempting = True
            except Exception:
                pass   # rtlint: disable=RT004 — poll again next tick
            if self._stop.wait(cfg.mpmd_health_poll_s):
                return

    def _chaos(self):
        from ray_tpu._private import rpc
        try:
            rpc._maybe_inject_failure("stage_step")
        except rpc.RpcError:
            # the chaos contract is a process DEATH mid-step, not a
            # catchable exception: survivors must recover from silence
            import signal
            os.kill(os.getpid(), signal.SIGKILL)

    # ------------------------------------------------------------- compute
    def forward(self, step, mb, x, target=None, chunk=0):
        self._chaos()
        return self._rts[chunk].forward(step, mb, x, target)

    def backward(self, step, mb, gy=None, chunk=0):
        self._chaos()
        return self._rts[chunk].backward(step, mb, gy)

    def apply_step(self, step):
        return [rt.apply_step(step) for rt in self._rts]

    def checkpoint(self, step):
        """Synchronous boundary snapshot (pre-async protocol)."""
        self.checkpoint_begin(step)
        return self.checkpoint_result(step)

    def checkpoint_begin(self, step):
        """Rides the ordered compute queue (so it lands exactly at the
        step boundary) but only captures references and hands the host
        copy + durable seal/put to background threads — the next step's
        compute is never behind a checkpoint write."""
        for rt in self._rts:
            rt.checkpoint_begin(step, on_sealed=self._sealed_hook(rt))
        return True

    def checkpoint_result(self, step):
        """Barrier + return the per-chunk snapshots (control group: the
        compute queue keeps draining while a caller waits here)."""
        return [rt.checkpoint_result(step) for rt in self._rts]

    def _sealed_hook(self, rt: StageRuntime):
        if not self._storage_dir():
            return None
        writer = self._ensure_shard_writer()
        root, vs = self._storage_path, rt.stage_idx

        def on_sealed(snap):
            writer.submit(root, vs, snap)
        return on_sealed

    def _ensure_shard_writer(self):
        if self._shard_writer is None:
            from ray_tpu.train.sharded_checkpoint import AsyncShardWriter
            self._shard_writer = AsyncShardWriter()
        return self._shard_writer

    def _storage_dir(self):
        return getattr(self, "_storage_path", None)

    def set_storage_path(self, path: Optional[str]):
        self._storage_path = path
        return True

    # ------------------------------------------------------------- control
    def abort_step(self, step):
        return all([rt.abort_step(step) for rt in self._rts])

    def rollback(self):
        # recovery is the shard-write barrier: a survivor's durable
        # state must be consistent before replay resumes over it
        if self._shard_writer is not None:
            try:
                self._shard_writer.barrier(timeout=60.0)
            except RuntimeError:
                import logging
                logging.getLogger(__name__).warning(
                    "stage %d: async shard write failed before rollback",
                    self._rts[0].stage_idx, exc_info=True)
        bounds = [rt.rollback() for rt in self._rts]
        if len(set(bounds)) != 1:
            raise RuntimeError(
                f"virtual chunks rolled back to different boundaries "
                f"{bounds}")
        return bounds[0]

    def compile_counts(self):
        return [rt.compile_counts() for rt in self._rts]

    def state_digest(self):
        return [rt.state_digest() for rt in self._rts]

    def ping(self):
        return True

    def preempting(self):
        return self._preempting

    def stop(self):
        self._stop.set()
        return True


# control methods answer while compute is queued: tag the group on the
# plain functions (actor.py reads __concurrency_group__ through
# ray_tpu.remote(), same as @ray_tpu.method(concurrency_group=...)).
# checkpoint_result is control-tagged on purpose: it BLOCKS on the
# background snapshot, and must not stall the ordered compute queue —
# checkpoint_begin stays on the compute queue so the capture lands
# exactly at the step boundary.
for _name in ("abort_step", "rollback", "compile_counts", "state_digest",
              "ping", "preempting", "stop", "set_storage_path",
              "checkpoint_result"):
    getattr(PipelineStageActor, _name).__concurrency_group__ = "control"
del _name


class ActorStageHandle:
    """Controller-side wrapper around a PipelineStageActor: methods
    return ObjectRefs (activations/grads stay in the object store — the
    controller passes refs between stages, never bytes)."""

    remote = True

    def __init__(self, stage_idx: int, actor):
        self.stage_idx = stage_idx
        self.actor = actor

    @classmethod
    def provision(cls, stage_idx: int, n_stages: int, n_microbatches: int,
                  builder: Optional[Callable] = None, snapshot=None,
                  preempt_marker: Optional[str] = None,
                  resources: Optional[Dict[str, float]] = None,
                  storage_path: Optional[str] = None,
                  chunk_builders: Optional[List[tuple]] = None,
                  donate: bool = True,
                  extra_options: Optional[Dict[str, Any]] = None
                  ) -> "ActorStageHandle":
        import ray_tpu
        opts: Dict[str, Any] = {
            "max_concurrency": 4,
            "concurrency_groups": {"control": 2},
        }
        if resources:
            opts["resources"] = dict(resources)
        if extra_options:
            opts.update(extra_options)
        actor = ray_tpu.remote(PipelineStageActor).options(**opts).remote(
            stage_idx, n_stages, n_microbatches, builder, snapshot,
            preempt_marker, chunk_builders, donate)
        h = cls(stage_idx, actor)
        if storage_path:
            h.fetch(actor.set_storage_path.remote(storage_path),
                    timeout=60.0)
        return h

    def forward(self, step, mb, x, target=None, chunk=0):
        return self.actor.forward.remote(step, mb, x, target, chunk)

    def backward(self, step, mb, gy=None, chunk=0):
        return self.actor.backward.remote(step, mb, gy, chunk)

    def apply_step(self, step):
        return self.actor.apply_step.remote(step)

    def abort_step(self, step):
        return self.actor.abort_step.remote(step)

    def checkpoint(self, step):
        return self.actor.checkpoint.remote(step)

    def checkpoint_begin(self, step):
        return self.actor.checkpoint_begin.remote(step)

    def checkpoint_result(self, step):
        return self.actor.checkpoint_result.remote(step)

    def rollback(self):
        return self.actor.rollback.remote()

    def compile_counts(self):
        return self.actor.compile_counts.remote()

    def state_digest(self):
        return self.actor.state_digest.remote()

    def ping(self, timeout: Optional[float] = 5.0) -> bool:
        import ray_tpu
        try:
            ray_tpu.get(self.actor.ping.remote(), timeout=timeout)
            return True
        except Exception:
            return False

    def preempting(self) -> bool:
        import ray_tpu
        try:
            return bool(ray_tpu.get(self.actor.preempting.remote(),
                                    timeout=5.0))
        except Exception:
            return False

    def kill(self):
        import ray_tpu
        try:
            ray_tpu.kill(self.actor)
        except Exception:
            pass

    def fetch(self, ref, timeout: Optional[float] = None):
        import ray_tpu
        return ray_tpu.get(ref, timeout=timeout)


# ------------------------------------------------------------- stage gangs

class _GangFanout:
    """Composite future over every gang member for one gang-consistent
    op: fetched as a unit (rank 0's value is the gang's value, the
    other ranks are verified/drained), plus the shadow futures of
    earlier rank-fanned compute ops that resolve at this barrier."""

    __slots__ = ("items", "shadow", "reduce")

    def __init__(self, items, shadow, reduce):
        self.items = items          # [(member, fut)] — values kept
        self.shadow = shadow        # [(member, fut)] — drained, discarded
        self.reduce = reduce        # List[value] -> gang value


class GangStageHandle:
    """One pipeline stage as a GANG of workers over one multi-host mesh
    — the Podracer slice-gang shape folded in from
    ``backend_executor`` (see :func:`acquire_slice_bundles`). Dispatch
    is gang-consistent: every compute op goes to ALL ranks in the same
    order, activations enter and leave through rank 0's arena (rank 0's
    output ref is what the neighbor stage consumes; the other ranks'
    outputs become shadow futures verified and drained at the step's
    apply barrier, so a straggler or diverged rank surfaces before the
    optimizer moves). State digests are gathered from every rank and
    must agree bit-for-bit; checkpoints ship rank 0's shard (the ranks
    are replicas of the same stage program). The preemption/park/replay
    lifecycle is unchanged — the gang fails, parks, restores and
    replays as a unit (any dead rank ⇒ the stage is lost ⇒ the whole
    gang is re-provisioned from the shard)."""

    def __init__(self, stage_idx: int, members: List[Any]):
        if not members:
            raise ValueError("a stage gang needs >= 1 member")
        self.stage_idx = stage_idx
        self.members = list(members)
        self.remote = bool(getattr(members[0], "remote", False))
        self._shadow: List[tuple] = []

    @classmethod
    def provision(cls, stage_idx: int, n_stages: int, n_microbatches: int,
                  chunk_builders: List[tuple], snapshot=None, *,
                  gang_size: int, topology: Optional[str] = None,
                  resources: Optional[Dict[str, float]] = None,
                  preempt_marker: Optional[str] = None,
                  storage_path: Optional[str] = None,
                  donate: bool = True) -> "GangStageHandle":
        """Provision a remote gang. With a ``topology``, the gang is
        pinned STRICT_SPREAD over one healthy multi-host slice via the
        executor's slice machinery; otherwise ranks schedule by
        ``resources`` alone."""
        per_rank_opts: List[Optional[Dict[str, Any]]] = \
            [None] * gang_size
        per_rank_res: List[Optional[Dict[str, float]]] = \
            [dict(resources) if resources else None] * gang_size
        if topology:
            from ray_tpu.train.backend_executor import acquire_slice_bundles
            from ray_tpu.util import (PlacementGroupSchedulingStrategy,
                                      placement_group)
            pod, bundles, strategy = acquire_slice_bundles(
                topology, resources or {}, num_workers=gang_size)
            if pod is not None:
                pg = placement_group(bundles, strategy=strategy)
                if not pg.wait(timeout=60):
                    raise RuntimeError(
                        f"stage {stage_idx}: gang placement group over "
                        f"{topology} not schedulable")
                per_rank_opts = [
                    {"scheduling_strategy": PlacementGroupSchedulingStrategy(
                        placement_group=pg, placement_group_bundle_index=r)}
                    for r in range(gang_size)]
                per_rank_res = [None] * gang_size   # the bundle carries it
        members = [
            ActorStageHandle.provision(
                stage_idx, n_stages, n_microbatches, None, snapshot,
                # only rank 0 watches the notice channel; preemption of
                # any gang host surfaces as a dead rank at the barrier
                preempt_marker=preempt_marker if r == 0 else None,
                resources=per_rank_res[r],
                storage_path=storage_path if r == 0 else None,
                chunk_builders=chunk_builders, donate=donate,
                extra_options=per_rank_opts[r])
            for r in range(gang_size)]
        return cls(stage_idx, members)

    # ------------------------------------------------------------- compute
    def _fanout_compute(self, submit) -> Any:
        futs = [submit(m) for m in self.members]
        self._shadow.extend(zip(self.members[1:], futs[1:]))
        return futs[0]

    def forward(self, step, mb, x, target=None, chunk=0):
        return self._fanout_compute(
            lambda m: m.forward(step, mb, x, target, chunk=chunk))

    def backward(self, step, mb, gy=None, chunk=0):
        return self._fanout_compute(
            lambda m: m.backward(step, mb, gy, chunk=chunk))

    def apply_step(self, step):
        shadow, self._shadow = self._shadow, []
        items = [(m, m.apply_step(step)) for m in self.members]

        def reduce(vals):
            norm = [[v] if isinstance(v, dict) else list(v) for v in vals]
            steps = {m.get("step") for chunks in norm for m in chunks}
            if len(steps) > 1:
                raise StageLostError(
                    self.stage_idx,
                    f"gang ranks applied different steps {sorted(steps)}")
            return norm[0]
        return _GangFanout(items, shadow, reduce)

    # ------------------------------------------------------------- control
    def abort_step(self, step):
        # parking discards the in-flight step everywhere, shadows too
        shadow, self._shadow = self._shadow, []
        items = [(m, m.abort_step(step)) for m in self.members]
        return _GangFanout(items, [], lambda vals: all(vals))

    def checkpoint(self, step):
        items = [(m, m.checkpoint(step)) for m in self.members]
        return _GangFanout(items, [], lambda vals: vals[0])

    def checkpoint_begin(self, step):
        # every rank snapshots (each needs its OWN boundary for
        # rollback); only rank 0's shard leaves the gang
        items = [(m, m.checkpoint_begin(step)) for m in self.members]
        return _GangFanout(items, [], lambda vals: all(vals))

    def checkpoint_result(self, step):
        # rank 0's arena is the gang's checkpoint arena
        return self.members[0].checkpoint_result(step)

    def rollback(self):
        items = [(m, m.rollback()) for m in self.members]

        def reduce(vals):
            if len(set(vals)) != 1:
                raise RuntimeError(
                    f"stage {self.stage_idx}: gang ranks rolled back to "
                    f"different boundaries {vals}")
            return vals[0]
        return _GangFanout(items, [], reduce)

    def compile_counts(self):
        items = [(m, m.compile_counts()) for m in self.members]
        return _GangFanout(items, [], lambda vals: vals[0])

    def state_digest(self):
        items = [(m, m.state_digest()) for m in self.members]

        def reduce(vals):
            norm = [[v] if isinstance(v, str) else list(v) for v in vals]
            if any(n != norm[0] for n in norm[1:]):
                raise RuntimeError(
                    f"stage {self.stage_idx}: gang rank states diverged "
                    "(replicated-stage invariant broken)")
            return norm[0]
        return _GangFanout(items, [], reduce)

    def ping(self, timeout: Optional[float] = 5.0) -> bool:
        return all(m.ping(timeout=timeout) for m in self.members)

    def preempting(self) -> bool:
        for m in self.members:
            try:
                if m.preempting():
                    return True
            except Exception:
                continue
        return False

    def kill(self):
        for m in self.members:
            try:
                m.kill()
            except Exception:
                pass   # rtlint: disable=RT004 — teardown best-effort

    def fetch(self, fut, timeout: Optional[float] = None):
        if isinstance(fut, _GangFanout):
            for m, f in fut.shadow:      # drain rank>0 compute outputs
                m.fetch(f, timeout=timeout)
            vals = [m.fetch(f, timeout=timeout) for m, f in fut.items]
            return fut.reduce(vals)
        return self.members[0].fetch(fut, timeout=timeout)


# -------------------------------------------------------------- controller

class MPMDPipelineTrainer:
    """Drives an S-stage MPMD pipeline over stage handles: ref-chained
    schedule dispatch, step-boundary shard checkpoints, and the
    stage-loss lifecycle (park → re-provision → restore → replay →
    rejoin).

    stage_builders: one callable per VIRTUAL stage returning its
        :class:`StageDefinition` (runs inside the stage's host process).
        With ``config.virtual_stages == v > 1`` the V = len(builders)
        virtual stages fold onto S = V // v physical stage hosts in the
        interleaved wrap: virtual stage vs lives on host vs % S as
        chunk vs // S.
    remote=True provisions a :class:`PipelineStageActor` gang (one
        actor per stage, ``stage_resources[s]`` pinning each to its
        slice); remote=False runs stages in-process (tests/probe).
    stage_gang_sizes[s] > 1 widens physical stage s into a
        :class:`GangStageHandle` of that many ranks (remote) or fake
        local members (in-process tests).
    provision_fn(stage_idx, snapshot) overrides stage provisioning
        entirely (tests inject failing handles through this)."""

    def __init__(self, stage_builders: List[Callable],
                 config: Optional[MPMDConfig] = None,
                 failure_config: Optional[FailureConfig] = None,
                 *, remote: bool = False,
                 stage_resources: Optional[List[Dict[str, float]]] = None,
                 stage_gang_sizes: Optional[List[int]] = None,
                 provision_fn: Optional[Callable] = None,
                 marker_dir: Optional[str] = None):
        self.builders = list(stage_builders)
        self.config = (config or MPMDConfig()).resolved()
        v = self.config.virtual_stages
        self.n_virtual = len(self.builders)
        if self.n_virtual % v:
            raise ValueError(
                f"virtual_stages={v} must divide the number of stage "
                f"builders ({self.n_virtual})")
        self.n_stages = self.n_virtual // v
        if self.n_stages < 2:
            raise ValueError("an MPMD pipeline needs >= 2 physical stages"
                             + (f" (got {self.n_virtual} builders at "
                                f"virtual_stages={v})" if v > 1 else ""))
        self.failure_config = failure_config or FailureConfig(
            max_failures=3, restart_policy="stage")
        self.remote = remote
        self.stage_resources = stage_resources or [None] * self.n_stages
        self.stage_gang_sizes = stage_gang_sizes or [1] * self.n_stages
        self._provision_fn = provision_fn
        self.schedule = make_schedule(self.config.schedule, self.n_stages,
                                      self.config.n_microbatches, virtual=v)
        self.replay = MicrobatchReplayBuffer(
            self.config.replay_depth,
            n_microbatches=self.config.n_microbatches,
            peak_live_buffers=[peak_live_activations(ops)
                               for ops in self.schedule])
        self.handles: List[Any] = []
        self.profiler = None
        self.last_stage_metrics: List[List[Dict[str, Any]]] = []
        self._snap_refs: Dict[int, Any] = {}   # stage -> snapshot ref/tree
        self._ckpt_step = 0
        self._failures_left = self.failure_config.max_failures
        self.recoveries: List[Dict[str, Any]] = []
        self.history: List[Dict[str, Any]] = []
        self._marker_dir = marker_dir
        self._markers: List[Optional[str]] = [None] * self.n_stages
        if marker_dir:
            os.makedirs(marker_dir, exist_ok=True)
            self._markers = [os.path.join(marker_dir, f"stage_{s}.preempt")
                             for s in range(self.n_stages)]

    # ---------------------------------------------------------- provision
    def _chunk_indices(self, stage_idx: int) -> List[int]:
        """Virtual-stage indices hosted by physical stage ``stage_idx``
        (the interleaved wrap: chunk c is virtual stage c*S + s)."""
        return [c * self.n_stages + stage_idx
                for c in range(self.config.virtual_stages)]

    def _provision(self, stage_idx: int, snapshot=None):
        if self._provision_fn is not None:
            return self._provision_fn(stage_idx, snapshot)
        return self._default_provision(stage_idx, snapshot)

    def _default_provision(self, stage_idx: int, snapshot=None):
        """The built-in stage host factory; provision_fn overrides can
        delegate here (it never re-enters the override)."""
        chunk_builders = [(vs, self.builders[vs])
                          for vs in self._chunk_indices(stage_idx)]
        gang = self.stage_gang_sizes[stage_idx]
        if self.remote:
            if gang > 1:
                return GangStageHandle.provision(
                    stage_idx, self.n_virtual, self.config.n_microbatches,
                    chunk_builders, snapshot, gang_size=gang,
                    resources=self.stage_resources[stage_idx],
                    preempt_marker=self._markers[stage_idx],
                    storage_path=self.config.storage_path,
                    donate=self.config.donate_buffers)
            return ActorStageHandle.provision(
                stage_idx, self.n_virtual, self.config.n_microbatches,
                None, snapshot,
                preempt_marker=self._markers[stage_idx],
                resources=self.stage_resources[stage_idx],
                storage_path=self.config.storage_path,
                chunk_builders=chunk_builders,
                donate=self.config.donate_buffers)
        if gang > 1:
            members = [LocalStageHandle(
                stage_idx, self.n_virtual, self.config.n_microbatches,
                None, snapshot,
                preempt_marker=self._markers[stage_idx] if r == 0 else None,
                chunk_builders=chunk_builders,
                donate=self.config.donate_buffers)
                for r in range(gang)]
            return GangStageHandle(stage_idx, members)
        return LocalStageHandle(
            stage_idx, self.n_virtual, self.config.n_microbatches,
            None, snapshot,
            preempt_marker=self._markers[stage_idx],
            chunk_builders=chunk_builders,
            donate=self.config.donate_buffers)

    def start(self):
        """Provision the stage gang and take the step-0 checkpoint (so a
        loss before the first boundary can still restore)."""
        if self.handles:
            return self
        self.handles = [self._provision(s) for s in range(self.n_stages)]
        self._checkpoint_all(0)
        return self

    def preempt_marker(self, stage_idx: int) -> Optional[str]:
        """The per-stage notice-file path (chaos/StageKiller channel)."""
        return self._markers[stage_idx]

    # -------------------------------------------------------------- fit
    def fit(self, data_fn: Callable[[int], tuple], n_steps: int
            ) -> Dict[str, Any]:
        """Run ``n_steps`` pipeline steps. ``data_fn(step)`` returns
        (inputs, targets): M first-stage input microbatches and M
        last-stage target microbatches. Returns the run summary."""
        from ray_tpu._private import events
        self.start()
        if self.config.step_profile and self.profiler is None:
            from ray_tpu.util.profiling import StepProfiler
            self.profiler = StepProfiler(name="mpmd", category="train")
        with events.record_span("train.mpmd.fit", category="train",
                                n_stages=self.n_stages,
                                n_virtual=self.n_virtual,
                                n_microbatches=self.config.n_microbatches,
                                schedule=self.config.schedule):
            step = 0
            while step < n_steps:
                step += 1
                scope = self.profiler.step() if self.profiler else None
                if scope is not None:
                    scope.__enter__()
                inputs, targets = data_fn(step)
                self._check_shapes(inputs, targets)
                self.replay.record(step, inputs, targets)
                if scope is not None:
                    scope.data_ready()
                self._run_step_with_recovery(step, inputs, targets)
                if scope is not None:
                    scope.__exit__(None, None, None)
                # checkpoint + migration run OUTSIDE the step scope: with
                # async_checkpoint they cost one fast ref round-trip here
                # and the residue shows up as the NEXT step's host_gap —
                # exactly the off-step signal the profiler attributes
                if step % self.config.checkpoint_every == 0:
                    self._checkpoint_all(step)
                self._migrate_preempting(step)
        return self.summary()

    def _check_shapes(self, inputs, targets):
        M = self.config.n_microbatches
        if len(inputs) != M or len(targets) != M:
            raise ValueError(
                f"data_fn must return {M} input + {M} target microbatches "
                f"(got {len(inputs)}/{len(targets)})")

    def summary(self) -> Dict[str, Any]:
        last = self.history[-1] if self.history else {}
        v = self.config.virtual_stages
        return {"steps": len({h["step"] for h in self.history}),
                "last_metrics": last,
                "history": self.history,
                "recoveries": self.recoveries,
                "schedule": self.config.schedule,
                "virtual_stages": v,
                "bubble_fraction_analytic": pipeline_bubble_fraction(
                    self.n_stages, self.config.n_microbatches, virtual=v),
                "bubble_fraction_analytic_plain": pipeline_bubble_fraction(
                    self.n_stages, self.config.n_microbatches),
                "peak_live_activations": [
                    peak_live_activations(ops) for ops in self.schedule],
                "replay_budget": self.replay.budget()}

    # ------------------------------------------------------ step execution
    def _run_step_with_recovery(self, step, inputs, targets):
        """Run one step; on stage loss, recover (park → replace →
        rollback) and replay the buffer — a loss DURING replay loops
        back into recovery against the same budget, so repeated chaos
        converges or degrades deterministically."""
        try:
            self._run_step(step, inputs, targets)
            return
        except StageLostError as e:
            lost, cause = e.stages, e.cause
        while True:
            t_rec = time.perf_counter()
            boundary = self._prepare_recovery(step, lost, cause)
            try:
                replayed = self.replay.replayable_from(boundary)
                for t in replayed:
                    ins, tgts = self.replay.get(t)
                    self._run_step(t, ins, tgts)
            except StageLostError as e:
                lost, cause = e.stages, e.cause
                continue
            self._note_recovery(step, lost, cause, boundary, replayed,
                                time.perf_counter() - t_rec)
            return

    def _run_step(self, step, inputs, targets):
        """Dispatch one step's full schedule ref-chained, then collect
        the per-stage apply barrier (per-chunk metrics per stage)."""
        from ray_tpu._private import events
        t0 = time.perf_counter()
        apply_futs = self._dispatch(step, inputs, targets)
        metrics = self._collect_applies(step, apply_futs)
        wall = time.perf_counter() - t0
        self.last_stage_metrics = metrics
        row: Dict[str, Any] = {"step": step, "wall_s": round(wall, 6)}
        total_flops = 0.0
        total_compute = 0.0
        for s, per_chunk in enumerate(metrics):
            comp = sum(m.get("compute_s", 0.0) for m in per_chunk)
            total_compute += comp
            total_flops += sum(m.get("flops", 0.0) for m in per_chunk)
            row[f"stage{s}_compute_s"] = round(comp, 6)
            row[f"stage{s}_bubble_fraction"] = round(
                max(0.0, 1.0 - comp / wall), 4) if wall else 0.0
            for m in per_chunk:
                if "loss" in m:
                    row["loss"] = m["loss"]
        self.history.append(row)
        if self.profiler is not None:
            if total_flops:
                self.profiler.set_cost(total_flops)
            self._emit_stage_gauges(row, wall, total_compute)
        events.record_instant(
            "train.mpmd.step", category="train", step=step,
            wall_ms=round(wall * 1e3, 3),
            **({"loss": row["loss"]} if "loss" in row else {}))
        return row

    def _emit_stage_gauges(self, row, wall, total_compute):
        """Per-stage compute/bubble/transfer attribution as
        ``runtime_mpmd_*`` gauges (the PR 7 gauges cover the step as a
        whole; these break the step open by physical stage)."""
        from ray_tpu.util.metrics import Gauge
        if not hasattr(self, "_stage_gauges"):
            self._stage_gauges = {
                "compute_ms": Gauge(
                    "runtime_mpmd_stage_compute_ms",
                    "per-stage on-device compute in the last step",
                    tag_keys=("stage",)),
                "bubble": Gauge(
                    "runtime_mpmd_stage_bubble_fraction",
                    "per-stage idle fraction of the last step wall",
                    tag_keys=("stage",)),
                "transfer_ms": Gauge(
                    "runtime_mpmd_transfer_ms",
                    "step wall not attributed to any stage's compute "
                    "(activation transfer + dispatch + collectives)"),
            }
        for s in range(self.n_stages):
            tags = {"stage": str(s)}
            self._stage_gauges["compute_ms"].set(
                row.get(f"stage{s}_compute_s", 0.0) * 1e3, tags=tags)
            self._stage_gauges["bubble"].set(
                row.get(f"stage{s}_bubble_fraction", 0.0), tags=tags)
        # stages overlap in time, so Σ compute can exceed wall; clamp —
        # the unclamped signal still lives in the per-stage gauges
        self._stage_gauges["transfer_ms"].set(
            max(0.0, wall - total_compute) * 1e3)

    def _dispatch(self, step, inputs, targets):
        """Ref-chain the schedule over the virtual-chunk dependency
        graph: virtual stage vs = c*S + s consumes activations from
        vs-1 (hosted on stage (vs-1) % S — possibly the SAME host's
        previous chunk) and gradients from vs+1. Keys are virtual-stage
        indices, so the plain path (v=1, vs == s) is unchanged."""
        S = self.n_stages
        V = self.n_virtual
        queues = [list(ops) for ops in self.schedule]
        fwd_out: Dict[tuple, Any] = {}
        bwd_out: Dict[tuple, Any] = {}
        while any(queues):
            progressed = False
            for s in range(S):
                while queues[s]:
                    op = queues[s][0]
                    kind, mb, c = op[0], op[1], op_chunk(op)
                    vs = c * S + s
                    if kind == OP_FWD:
                        if vs == 0:
                            x = inputs[mb]
                        elif (vs - 1, mb) in fwd_out:
                            x = fwd_out[(vs - 1, mb)]
                        else:
                            break
                        tgt = targets[mb] if vs == V - 1 else None
                        fwd_out[(vs, mb)] = self.handles[s].forward(
                            step, mb, x, tgt, chunk=c)
                    else:
                        if vs < V - 1 and (vs + 1, mb) not in bwd_out:
                            break
                        gy = bwd_out[(vs + 1, mb)] if vs < V - 1 else None
                        bwd_out[(vs, mb)] = self.handles[s].backward(
                            step, mb, gy, chunk=c)
                    queues[s].pop(0)
                    progressed = True
            if not progressed:
                raise ValueError("pipeline schedule deadlocked in dispatch")
        return [h.apply_step(step) for h in self.handles]

    def _collect_applies(self, step, apply_futs):
        """Fetch every stage's apply barrier. Returns one per-chunk
        metrics LIST per stage (single-chunk handles that return a bare
        dict are normalized)."""
        metrics, first_err = [], None
        for s, fut in enumerate(apply_futs):
            try:
                got = self.handles[s].fetch(
                    fut, timeout=self.config.step_timeout_s)
                metrics.append([got] if isinstance(got, dict) else list(got))
            except Exception as e:
                if first_err is None:
                    first_err = (s, e)
        if first_err is not None:
            lost = [s for s, h in enumerate(self.handles)
                    if not h.ping(timeout=5.0)]
            raise StageLostError(
                lost[0] if lost else first_err[0],
                f"{type(first_err[1]).__name__}: {first_err[1]}",
                stages=lost or [first_err[0]])
        return metrics

    # ------------------------------------------------------- checkpointing
    def _checkpoint_all(self, step):
        """Step-boundary checkpoint of every stage. Async mode
        (config.async_checkpoint) splits the protocol: fetch the cheap
        ``checkpoint_begin`` acks (capture happens at the boundary, the
        host copy runs on each stage's background thread), then store
        the ``checkpoint_result`` futures UNRESOLVED — the barrier that
        waits for the sealed snapshot moves to the recovery path."""
        if self.config.async_checkpoint:
            begun = [(s, h.checkpoint_begin(step))
                     for s, h in enumerate(self.handles)]
            for s, fut in begun:
                self.handles[s].fetch(fut, timeout=60.0)
            for s, h in enumerate(self.handles):
                self._snap_refs[s] = h.checkpoint_result(step)
        else:
            futs = [h.checkpoint(step) for h in self.handles]
            for s, fut in enumerate(futs):
                if self.handles[s].remote:
                    # keep the REF: the snapshot object stays in the
                    # arena (cross-node restores ride the data plane);
                    # fetching it to the controller would defeat the
                    # zero-copy path
                    self._snap_refs[s] = fut
                else:
                    self._snap_refs[s] = self.handles[s].fetch(fut)
        self._ckpt_step = step

    def _resolve_snap(self, stage_idx: int):
        """Materialize a stored snapshot entry for a LOCAL restore
        (async mode parks _Later/_Now thunks; resolving one is the
        recovery-time barrier)."""
        snap = self._snap_refs.get(stage_idx)
        if snap is not None and hasattr(snap, "result"):
            snap = self._snap_refs[stage_idx] = snap.result()
        return snap

    def _restore_source(self, stage_idx: int):
        """Recovery ladder for a replacement stage's shard: object-store
        snapshot ref first; durable storage shard (one host reads, the
        weight plane fans out — sharded_checkpoint.restore_and_broadcast)
        when the ref is gone."""
        remote = bool(self.handles and
                      getattr(self.handles[stage_idx], "remote", False))
        if not remote:
            snap = self._resolve_snap(stage_idx)
        else:
            snap = self._snap_refs.get(stage_idx)
            if snap is not None:
                try:
                    # probe the ref is still materializable (the dead
                    # stage's node may have taken it down with it)
                    import ray_tpu
                    ready, _ = ray_tpu.wait([snap], num_returns=1,
                                            timeout=5.0)
                    if not ready:
                        snap = None
                except Exception:
                    snap = None
        if snap is not None:
            return snap
        if self.config.storage_path:
            from ray_tpu.train.sharded_checkpoint import (
                restore_stage_shard)
            shards = [restore_stage_shard(self.config.storage_path, vs,
                                          broadcast=self.remote)
                      for vs in self._chunk_indices(stage_idx)]
            return shards[0] if len(shards) == 1 else shards
        raise PipelineDegradedError(
            f"no restore source for stage {stage_idx} (snapshot ref lost "
            "and no storage_path configured)")

    # ------------------------------------------------------------ recovery
    def _prepare_recovery(self, step, lost: List[int], cause: str = ""
                          ) -> int:
        """Budget check → park survivors at the bounded barrier →
        re-provision lost stages from their shards → roll survivors back
        to the checkpoint boundary. Returns the boundary step the replay
        must start after. Raises PipelineDegradedError when stage-level
        recovery cannot proceed (policy/budget/barrier)."""
        from ray_tpu._private import events
        policy = getattr(self.failure_config, "restart_policy", "job")
        if policy != "stage":
            raise PipelineDegradedError(
                f"stage {lost} lost at step {step} and "
                f"restart_policy={policy!r}: job-level restart required")
        if self._failures_left <= 0:
            raise PipelineDegradedError(
                f"stage {lost} lost at step {step}: failure budget "
                f"exhausted (max_failures="
                f"{self.failure_config.max_failures})")
        self._failures_left -= 1
        events.record_instant(
            "train.mpmd.stage_lost", category="train", step=step,
            stages=",".join(map(str, lost)), cause=cause[:200])
        time.sleep(getattr(self.failure_config, "restart_backoff_s", 0.0)
                   or 0.0)

        # 1. park survivors at the bounded-deadline barrier
        survivors = [s for s in range(self.n_stages) if s not in lost]
        deadline = time.monotonic() + self.config.barrier_deadline_s
        barrier = [(s, self.handles[s].abort_step(step)) for s in survivors]
        stragglers = []
        for s, fut in barrier:
            left = deadline - time.monotonic()
            try:
                self.handles[s].fetch(fut, timeout=max(0.1, left))
            except Exception:
                stragglers.append(s)
        if stragglers:
            raise PipelineDegradedError(
                f"survivors {stragglers} missed the "
                f"{self.config.barrier_deadline_s}s park barrier after "
                f"stage {lost} loss — degrading to job-level restart")

        # 2. re-provision lost stages from their shard checkpoints
        for s in lost:
            try:
                self.handles[s].kill()
            except Exception:
                pass   # rtlint: disable=RT004 — corpse may be gone
            self.handles[s] = self._provision(s, self._restore_source(s))

        # 3. roll surviving stages back to the checkpoint boundary
        boundary = self._ckpt_step
        roll = [(s, self.handles[s].rollback()) for s in survivors]
        for s, fut in roll:
            got = self.handles[s].fetch(fut, timeout=60.0)
            if got != boundary:
                raise PipelineDegradedError(
                    f"stage {s} rolled back to step {got}, controller "
                    f"checkpoint boundary is {boundary}")
        return boundary

    def _note_recovery(self, step, lost, cause, boundary, replayed,
                       recovery_s):
        from ray_tpu._private import events
        self.recoveries.append({
            "step": step, "stages": list(lost), "cause": cause,
            "boundary": boundary, "replayed_steps": list(replayed),
            "steps_lost": len(replayed),
            "recovery_s": round(recovery_s, 3)})
        events.record_instant(
            "train.mpmd.stage_rejoined", category="train", step=step,
            stages=",".join(map(str, lost)), boundary=boundary,
            steps_replayed=len(replayed),
            recovery_ms=round(recovery_s * 1e3, 1))

    # --------------------------------------------------- graceful migration
    def _migrate_preempting(self, step):
        """Boundary-time migration for stages whose host got a
        preemption NOTICE (watch thread / marker file): fresh
        checkpoint, replacement provisioned from it, old actor reaped —
        zero replayed steps, optimizer state untouched."""
        preempting = []
        for s, h in enumerate(self.handles):
            try:
                if h.preempting():
                    preempting.append(s)
            except Exception:
                continue
        if not preempting:
            return
        from ray_tpu._private import events
        self._checkpoint_all(step)
        for s in preempting:
            old = self.handles[s]
            snap = (self._snap_refs[s] if old.remote
                    else self._resolve_snap(s))
            self.handles[s] = self._provision(s, snap)
            try:
                old.kill()
            except Exception:
                pass   # rtlint: disable=RT004 — host is going away anyway
            if self._markers[s]:
                try:
                    os.remove(self._markers[s])
                except FileNotFoundError:
                    pass
            events.record_instant(
                "train.mpmd.stage_migrated", category="train", step=step,
                stage=s)

    # ------------------------------------------------------------- queries
    def _flatten_virtual(self, per_stage: List[Any]) -> List[Any]:
        """Reorder per-stage per-chunk lists into VIRTUAL-stage order
        (out[c*S + s] = stage s's chunk c) — the order a plain v=1 run
        over V single-chunk stages would report, so digests compare
        directly across schedules."""
        S, v = self.n_stages, self.config.virtual_stages
        norm = [[x] if not isinstance(x, list) else x for x in per_stage]
        out: List[Any] = [None] * self.n_virtual
        for s, chunks in enumerate(norm):
            if len(chunks) != v:
                raise RuntimeError(
                    f"stage {s} reported {len(chunks)} chunks, "
                    f"expected {v}")
            for c, val in enumerate(chunks):
                out[c * S + s] = val
        return out

    def compile_counts(self) -> List[Dict[str, int]]:
        """Per-VIRTUAL-stage compile counters (virtual-stage order)."""
        futs = [h.compile_counts() for h in self.handles]
        got = [self.handles[s].fetch(f, timeout=30.0)
               for s, f in enumerate(futs)]
        return self._flatten_virtual(got)

    def state_digests(self) -> List[str]:
        """Per-VIRTUAL-stage state digests (virtual-stage order) —
        directly comparable between a v>1 run and a plain run over the
        same V builders."""
        futs = [h.state_digest() for h in self.handles]
        got = [self.handles[s].fetch(f, timeout=60.0)
               for s, f in enumerate(futs)]
        return self._flatten_virtual(got)

    def shutdown(self):
        for h in self.handles:
            members = getattr(h, "members", [h])
            for m in members:
                try:
                    if m.remote and hasattr(m, "actor"):
                        m.fetch(m.actor.stop.remote(), timeout=5.0)
                    m.kill()
                except Exception:
                    pass   # rtlint: disable=RT004 — teardown best-effort
        self.handles = []
