from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (CheckpointConfig, FailureConfig, RunConfig,
                                  ScalingConfig)
from ray_tpu.train.session import get_context, report
from ray_tpu.train.trainer import JaxTrainer, Result

__all__ = ["JaxTrainer", "Result", "ScalingConfig", "RunConfig",
           "FailureConfig", "CheckpointConfig", "Checkpoint", "report",
           "get_context"]
