from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (CheckpointConfig, DataConfig,
                                  FailureConfig, RunConfig,
                                  ScalingConfig)
from ray_tpu.train.session import (get_checkpoint, get_context,
                                   get_dataset_shard, report,
                                   step_profiler)
from ray_tpu.train.trainer import JaxTrainer, Result

__all__ = ["JaxTrainer", "Result", "ScalingConfig", "RunConfig",
           "FailureConfig", "CheckpointConfig", "DataConfig", "Checkpoint",
           "report", "get_context", "get_checkpoint", "get_dataset_shard",
           "step_profiler", "MPMDPipelineTrainer", "MPMDConfig",
           "StageDefinition"]


def __getattr__(name):
    # mpmd pulls in jax-facing machinery; load it on first touch so
    # `import ray_tpu.train` stays light for config-only consumers
    if name in ("MPMDPipelineTrainer", "MPMDConfig", "StageDefinition"):
        from ray_tpu.train import mpmd as _mpmd
        return getattr(_mpmd, name)
    raise AttributeError(f"module 'ray_tpu.train' has no attribute {name!r}")
