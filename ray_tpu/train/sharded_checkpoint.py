"""Sharded JAX checkpointing via orbax: save/restore a mesh-sharded
TrainState without gathering it to one host.

TPU-native replacement for torch checkpointing inside the reference's
train loop (reference: Checkpoint/StorageContext
python/ray/train/_internal/storage.py — there a directory of torch
files; here each host writes only its shards through orbax/tensorstore,
and restore places shards by the target NamedShardings — the multi-host
path the reference delegates to torch.distributed checkpoint).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def save_sharded(state: Any, path: str, *, force: bool = True) -> str:
    """Write a (possibly sharded) pytree of jax.Arrays; every process
    writes its own shards (orbax handles coordination)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=force)
    ckptr.wait_until_finished()
    return path


def restore_sharded(path: str, abstract_state: Any) -> Any:
    """Restore into the shardings of `abstract_state` — a pytree of
    jax.ShapeDtypeStruct with `.sharding` set (e.g. from
    jax.eval_shape + NamedShardings), so every host reads only the
    shards it owns."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, abstract_state)


def abstract_like(state: Any, shardings: Optional[Any] = None) -> Any:
    """Build the abstract (shape/dtype/sharding) tree restore_sharded
    needs, from a concrete state or from (eval_shape tree, shardings)."""
    def mk(x, sh):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
    if shardings is None:
        return jax.tree.map(lambda x: mk(x, x.sharding), state)
    return jax.tree.map(mk, state, shardings)
