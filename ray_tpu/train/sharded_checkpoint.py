"""Sharded JAX checkpointing via orbax: save/restore a mesh-sharded
TrainState without gathering it to one host.

TPU-native replacement for torch checkpointing inside the reference's
train loop (reference: Checkpoint/StorageContext
python/ray/train/_internal/storage.py — there a directory of torch
files; here each host writes only its shards through orbax/tensorstore,
and restore places shards by the target NamedShardings — the multi-host
path the reference delegates to torch.distributed checkpoint).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def save_sharded(state: Any, path: str, *, force: bool = True) -> str:
    """Write a (possibly sharded) pytree of jax.Arrays; every process
    writes its own shards (orbax handles coordination)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=force)
    ckptr.wait_until_finished()
    return path


def restore_sharded(path: str, abstract_state: Any) -> Any:
    """Restore into the shardings of `abstract_state` — a pytree of
    jax.ShapeDtypeStruct with `.sharding` set (e.g. from
    jax.eval_shape + NamedShardings), so every host reads only the
    shards it owns."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, abstract_state)


def abstract_like(state: Any, shardings: Optional[Any] = None) -> Any:
    """Build the abstract (shape/dtype/sharding) tree restore_sharded
    needs, from a concrete state or from (eval_shape tree, shardings)."""
    def mk(x, sh):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
    if shardings is None:
        return jax.tree.map(lambda x: mk(x, x.sharding), state)
    return jax.tree.map(mk, state, shardings)


# ------------------------------------------------- broadcast-backed restore
# Cold-start/elastic-restart shape: ONE host reads the checkpoint off
# storage, then the weight-distribution plane fans the host-memory tree
# out to every node as a single sealed (spanning, if multi-GB) arena
# object over the log-depth relay tree — N-1 hosts hit their local arena
# instead of N hosts hammering the checkpoint bucket, and the restore
# cost is one storage read + one broadcast regardless of fleet size.

def restore_and_broadcast(path: str, abstract_state: Any = None,
                          node_ids: Optional[Any] = None):
    """Restore a checkpoint on THIS host and pre-position it cluster-wide
    via ``ray_tpu.broadcast_weights``. Returns the ObjectRef every other
    host passes to :func:`restore_from_broadcast`.

    ``abstract_state=None`` restores raw (numpy) leaves — the right form
    for broadcasting, since device placement happens per-host at attach
    time anyway. With an abstract tree the restored (host-side) arrays
    are broadcast as-is."""
    import numpy as np

    import ray_tpu
    if abstract_state is None:
        state = restore_host_arrays(path)
    else:
        state = restore_sharded(path, abstract_state)
        # pull shards to host memory so the broadcast payload is plain
        # buffers, not device handles
        state = jax.tree.map(np.asarray, state)
    return ray_tpu.broadcast_weights(state, node_ids=node_ids)


def restore_host_arrays(path: str) -> Any:
    """Read a checkpoint into host (numpy) arrays with no sharding
    placement — the broadcastable form of the state."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path)


# ------------------------------------------------- MPMD stage shards
# The elastic pipeline trainer (train/mpmd.py) checkpoints each stage's
# (params, opt_state) shard at step boundaries. The object-store
# snapshot ref is the fast path; these durable shards are the fallback
# when the ref died with the stage's node. The write is a plain
# cloudpickle blob through util.storage (one shard = one stage = one
# host; there is nothing to coordinate, so orbax's multi-host machinery
# would be pure overhead here).

def _stage_shard_path(root: str, stage_idx: int) -> str:
    from ray_tpu.util import storage as _storage
    return _storage.join(root, f"stage_{stage_idx:03d}", "shard.pkl")


def save_stage_shard(root: str, stage_idx: int, snapshot: Any) -> str:
    """Persist one pipeline stage's host-array snapshot under
    ``root/stage_NNN/`` (local path or fsspec URI). Overwrites the
    previous boundary — the replay buffer only ever needs the latest."""
    import cloudpickle

    from ray_tpu.util import storage as _storage
    path = _stage_shard_path(root, stage_idx)
    _storage.makedirs(_storage.join(root, f"stage_{stage_idx:03d}"))
    _storage.write_bytes(path, cloudpickle.dumps(snapshot))
    return path


class AsyncShardWriter:
    """Off-step durable shard writes for the elastic MPMD pipeline:
    ``submit()`` enqueues a stage's latest boundary snapshot and returns
    immediately; one daemon thread seals/puts the blobs through
    :func:`save_stage_shard`, so the training hot path never waits on
    storage. A newer submission for the same stage supersedes a queued
    older one (only the latest boundary matters for recovery — same
    rule as the overwrite in ``save_stage_shard``). ``barrier()`` drains
    the queue and is called only on the recovery path, never per step;
    write failures are remembered and surfaced there (the shards are
    the FALLBACK restore source behind the object-store snapshot ref,
    so a best-effort miss degrades, it does not corrupt)."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._pending: dict = {}          # (root, stage_idx) -> snapshot
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._stop = False
        self._thread = None
        self.last_error: Optional[BaseException] = None
        self.writes = 0

    def _ensure_thread(self):
        import threading
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="shard-writer", daemon=True)
            self._thread.start()

    def _loop(self):
        while True:
            self._wake.wait()
            with self._lock:
                if self._stop and not self._pending:
                    return
                if not self._pending:
                    self._wake.clear()
                    self._idle.set()
                    continue
                key, snap = next(iter(self._pending.items()))
                del self._pending[key]
            try:
                save_stage_shard(key[0], key[1], snap)
                with self._lock:
                    self.writes += 1
            except BaseException as e:   # surfaced at the next barrier
                with self._lock:
                    self.last_error = e

    def submit(self, root: str, stage_idx: int, snapshot: Any):
        with self._lock:
            self._pending[(root, stage_idx)] = snapshot
            self._idle.clear()
        self._wake.set()
        self._ensure_thread()

    def barrier(self, timeout: Optional[float] = None) -> bool:
        """Drain queued writes (recovery-time only). Returns False on
        timeout; re-raises the last write error, if any, exactly once."""
        if self._thread is None:
            drained = True
        else:
            drained = self._idle.wait(timeout)
        with self._lock:
            err, self.last_error = self.last_error, None
        if err is not None:
            raise RuntimeError("async stage-shard write failed") from err
        return drained

    def stop(self):
        with self._lock:
            self._stop = True
        self._wake.set()


def restore_stage_shard(root: str, stage_idx: int,
                        broadcast: bool = False):
    """Read one stage shard back. ``broadcast=True`` (cluster recovery)
    routes the tree through ``ray_tpu.broadcast_weights`` and returns
    the ObjectRef — the replacement stage attaches from its local arena
    (``restore_and_broadcast``'s shape, scoped to one shard) with a
    plain-put fallback when the weight plane is unavailable.
    ``broadcast=False`` returns the snapshot tree itself."""
    import cloudpickle

    from ray_tpu.util import storage as _storage
    snap = cloudpickle.loads(
        _storage.read_bytes(_stage_shard_path(root, stage_idx)))
    if not broadcast:
        return snap
    import ray_tpu
    try:
        return ray_tpu.broadcast_weights(snap)
    except Exception:
        # weight plane unavailable (single node, no data plane): the
        # plain put still parks the shard arena-side for the attach
        return ray_tpu.put(snap)


def restore_from_broadcast(ref, abstract_state: Any = None) -> Any:
    """Materialize a broadcast checkpoint on this host: a zero-copy get
    from the local arena (the broadcast already landed the bytes here),
    then optional placement onto this host's shardings."""
    import ray_tpu
    state = ray_tpu.get(ref)
    if abstract_state is None:
        return state

    def place(x, ab):
        sh = getattr(ab, "sharding", None)
        if sh is None:
            return jax.numpy.asarray(x, dtype=ab.dtype)
        return jax.device_put(jax.numpy.asarray(x, dtype=ab.dtype), sh)
    return jax.tree.map(place, state, abstract_state)
