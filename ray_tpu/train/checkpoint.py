"""Checkpoints: a directory of files, moved via the object store
(reference: python/ray/train/_checkpoint.py — dir + pyarrow fs; here the
transport is the shared-memory object store and persistence is a local /
NFS / fuse path; orbax handles sharded jax arrays)."""

from __future__ import annotations

import os
import shutil
import tarfile
import tempfile
import uuid
from io import BytesIO
from typing import Any, Dict, Optional


class Checkpoint:
    """Either a path-backed or bytes-backed (in object store) checkpoint."""

    def __init__(self, path: Optional[str] = None,
                 _blob: Optional[bytes] = None,
                 metrics: Optional[Dict[str, Any]] = None):
        self.path = path
        self._blob = _blob
        self.metrics = metrics or {}

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        buf = BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            tar.add(path, arcname=".")
        return cls(_blob=buf.getvalue())

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        import cloudpickle
        with tempfile.TemporaryDirectory() as d:
            with open(os.path.join(d, "data.pkl"), "wb") as f:
                cloudpickle.dump(data, f)
            return cls.from_directory(d)

    def to_directory(self, path: Optional[str] = None) -> str:
        from ray_tpu.util import storage as _storage
        if path is None:
            path = tempfile.mkdtemp(prefix="rt_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._blob is not None:
            with tarfile.open(fileobj=BytesIO(self._blob)) as tar:
                tar.extractall(path, filter="data")
        elif self.path is not None and _storage.is_remote(self.path):
            # URI-persisted checkpoint: single tar object (see persist)
            tar_uri = _storage.join(self.path, "ckpt.tar")
            if _storage.exists(tar_uri):
                raw = _storage.read_bytes(tar_uri)
                with tarfile.open(fileobj=BytesIO(raw)) as tar:
                    tar.extractall(path, filter="data")
            else:
                _storage.download_dir(self.path, path)
        elif self.path is not None and os.path.abspath(self.path) != \
                os.path.abspath(path):
            shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    def to_dict(self) -> Dict[str, Any]:
        import cloudpickle
        with tempfile.TemporaryDirectory() as d:
            self.to_directory(d)
            with open(os.path.join(d, "data.pkl"), "rb") as f:
                return cloudpickle.load(f)

    def persist(self, storage_dir: str, name: Optional[str] = None) -> str:
        """Write this checkpoint under storage_dir (local path or any
        fsspec URI — gs://bucket/exp on real pods; reference: Train's
        StorageContext uploads to pyarrow filesystems). Returns the new
        path/URI."""
        from ray_tpu.util import storage as _storage
        _storage.validate_root(storage_dir, "checkpoint")
        name = name or f"checkpoint_{uuid.uuid4().hex[:8]}"
        if _storage.is_remote(storage_dir):
            uri = _storage.join(storage_dir, name)
            blob = self._blob
            if blob is None:
                tar_uri = _storage.join(self.path, "ckpt.tar")
                if _storage.is_remote(self.path):
                    # already tarred at the source URI: copy the bytes
                    # (tar.add only reads local paths anyway)
                    blob = _storage.read_bytes(tar_uri)
                else:
                    buf = BytesIO()
                    with tarfile.open(fileobj=buf, mode="w") as tar:
                        tar.add(self.path, arcname=".")
                    blob = buf.getvalue()
            _storage.write_bytes(_storage.join(uri, "ckpt.tar"), blob)
            self.path = uri
            self._blob = None
            return uri
        path = os.path.join(storage_dir, name)
        self.to_directory(path)
        self.path = path
        self._blob = None
        return path

    def __reduce__(self):
        return (Checkpoint, (self.path, self._blob, self.metrics))


class CheckpointManager:
    """Top-k retention by score (reference:
    python/ray/train/_internal/checkpoint_manager.py)."""

    def __init__(self, storage_dir: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, order: str = "max"):
        from ray_tpu.util import storage as _storage
        _storage.validate_root(storage_dir, "checkpoint")
        self.storage_dir = storage_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.order = order
        self.checkpoints = []   # [(score, path, metrics)]
        self._counter = 0
        _storage.makedirs(storage_dir)

    def register(self, ckpt: Checkpoint, metrics: Dict[str, Any]) -> str:
        self._counter += 1
        path = ckpt.persist(self.storage_dir,
                            f"checkpoint_{self._counter:06d}")
        score = None
        if self.score_attribute:
            score = metrics.get(self.score_attribute)
        self.checkpoints.append((score, path, dict(metrics)))
        self._enforce_retention()
        return path

    def _enforce_retention(self):
        if self.num_to_keep is None or \
                len(self.checkpoints) <= self.num_to_keep:
            return
        if self.score_attribute:
            # unscored checkpoints must rank BELOW every scored one in
            # either direction
            if self.order == "max":
                ranked = sorted(
                    self.checkpoints,
                    key=lambda t: (t[0] is not None,
                                   t[0] if t[0] is not None
                                   else float("-inf")),
                    reverse=True)
            else:
                ranked = sorted(
                    self.checkpoints,
                    key=lambda t: (t[0] is None,
                                   t[0] if t[0] is not None
                                   else float("inf")))
        else:
            ranked = list(self.checkpoints)   # FIFO: oldest dropped
            ranked = ranked[::-1]
        keep = set(id(t) for t in ranked[:self.num_to_keep])
        from ray_tpu.util import storage as _storage
        for t in list(self.checkpoints):
            if id(t) not in keep:
                if _storage.is_remote(t[1]):
                    _storage.delete_dir(t[1])
                else:
                    shutil.rmtree(t[1], ignore_errors=True)
                self.checkpoints.remove(t)

    def best_checkpoint(self):
        if not self.checkpoints:
            return None
        if self.score_attribute:
            scored = [t for t in self.checkpoints if t[0] is not None]
            if scored:
                best = (max if self.order == "max" else min)(
                    scored, key=lambda t: t[0])
                return Checkpoint(path=best[1], metrics=best[2])
        t = self.checkpoints[-1]
        return Checkpoint(path=t[1], metrics=t[2])

    def latest_checkpoint(self):
        if not self.checkpoints:
            return None
        t = self.checkpoints[-1]
        return Checkpoint(path=t[1], metrics=t[2])
