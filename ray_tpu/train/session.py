"""Per-worker training session (reference:
python/ray/train/_internal/session.py:111 _TrainSession — report/checkpoint
queue :403). `report()` is called from the user's training loop inside a
worker actor; results buffer in the actor and the driver drains them."""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclasses.dataclass
class TrainContext:
    world_size: int
    world_rank: int
    local_rank: int
    node_rank: int
    trial_name: str = "train"
    experiment_name: str = "train"

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank


class _Session:
    def __init__(self, context: TrainContext):
        self.context = context
        self.results: List[Dict[str, Any]] = []
        self.lock = threading.Lock()
        self.latest_checkpoint: Optional[Checkpoint] = None
        self.dataset_shards: Dict[str, Any] = {}

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None):
        with self.lock:
            self.results.append({"metrics": dict(metrics),
                                 "checkpoint": checkpoint})

    def drain(self) -> List[Dict[str, Any]]:
        with self.lock:
            out = self.results
            self.results = []
            return out


_session: Optional[_Session] = None


def _init_session(context: TrainContext) -> _Session:
    global _session
    _session = _Session(context)
    return _session


def _shutdown_session():
    global _session
    _session = None


def get_session() -> Optional[_Session]:
    return _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (rank 0's checkpoint is persisted by the driver)."""
    s = _session
    if s is None:
        raise RuntimeError("ray_tpu.train.report() called outside a "
                           "training worker")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = _session
    if s is None:
        raise RuntimeError("not inside a training worker")
    return s.context


def get_checkpoint() -> Optional[Checkpoint]:
    s = _session
    return s.latest_checkpoint if s else None


_step_profiler = None


def step_profiler():
    """The training loop's StepProfiler (util/profiling.py), named
    ``train_step`` so its gauges land as ``runtime_train_step_mfu`` +
    phase attribution. One per process: inside a training worker every
    epoch shares it; outside (bare scripts, tests) it still works — the
    gauges just push from whatever process runs the loop.

    Usage inside ``train_loop_per_worker``::

        prof = ray_tpu.train.step_profiler()
        step = prof.wrap_jit(jitted_step)          # cost_analysis FLOPs
        for batch in loader:
            with prof.step(tokens=batch.size) as s:
                s.data_ready()
                state, metrics = step(state, batch)
                s.block(metrics["loss"])
    """
    global _step_profiler
    if _step_profiler is None:
        from ray_tpu.util.profiling import StepProfiler
        _step_profiler = StepProfiler("train_step")
    return _step_profiler


def get_dataset_shard(name: str = "train"):
    """This worker's streaming shard of a Dataset passed to the trainer
    (reference: ray.train.get_dataset_shard — DataIterator per worker)."""
    s = _session
    if s is None:
        raise RuntimeError("not inside a training worker")
    shard = s.dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"no dataset shard {name!r}; trainer datasets: "
            f"{sorted(s.dataset_shards)}")
    return shard
