"""JaxTrainer: distributed data/model-parallel training driver
(reference shape: python/ray/train/data_parallel_trainer.py:25 — worker
group, per-worker sessions, checkpointing, group restart on failure; the
reference routes fit() through Tune (base_trainer.py:567) — here fit() is
self-contained and the Tune integration wraps it instead)."""

from __future__ import annotations

import dataclasses
import os
import tempfile
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.backend_executor import BackendExecutor
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (CheckpointConfig, DataConfig,
                                  FailureConfig, RunConfig, ScalingConfig)


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    path: str
    error: Optional[BaseException] = None
    metrics_history: Optional[list] = None


class JaxTrainer:
    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 dataset_config: Optional["DataConfig"] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.dataset_config = dataset_config
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        from ray_tpu._private import events
        rc = self.run_config
        name = rc.name or f"train_{int(time.time())}"
        with events.record_span("train.fit", category="train",
                                run_name=name):
            return self._fit(name, rc)

    def _fit(self, name: str, rc) -> Result:
        from ray_tpu._private import events
        from ray_tpu.util import storage as _storage
        storage = rc.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results")
        exp_dir = _storage.join(storage, name)
        _storage.makedirs(exp_dir)
        ckpt_cfg = rc.checkpoint_config or CheckpointConfig()
        manager = CheckpointManager(
            _storage.join(exp_dir, "checkpoints"),
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            order=ckpt_cfg.checkpoint_score_order)
        failure_cfg = rc.failure_config or FailureConfig()
        failures_left = failure_cfg.max_failures
        resume = self.resume_from_checkpoint

        history: list = []
        last_metrics: Dict[str, Any] = {}
        while True:
            executor = BackendExecutor(
                self.scaling_config,
                use_jax_distributed=(
                    self.scaling_config.jax_distributed_enabled()))
            error = None
            try:
                executor.start()
                if resume is not None:
                    executor.set_resume_checkpoint(resume)
                if self.datasets:
                    executor.setup_datasets(self.datasets,
                                            self.dataset_config)
                executor.start_training(self.train_loop,
                                        self.train_loop_config)
                while True:
                    for rank, results in enumerate(executor.poll_results()):
                        for item in results:
                            metrics = item["metrics"]
                            ckpt = item["checkpoint"]
                            if rank == 0:
                                metrics = {**metrics,
                                           "_timestamp": time.time()}
                                history.append(metrics)
                                last_metrics = metrics
                                # reported train metrics become timeline
                                # instants so loss/MFU curves line up
                                # with the runtime spans around them
                                events.record_instant(
                                    "train.report", category="train",
                                    run_name=name,
                                    **{k: v for k, v in metrics.items()
                                       if isinstance(v, (int, float))})
                                if ckpt is not None:
                                    manager.register(ckpt, metrics)
                    done, error = executor.finished()
                    if done and error is not None \
                            and failure_cfg.restart_policy == "stage" \
                            and failures_left > 0 \
                            and executor.supports_worker_replace():
                        # per-worker replace: only the dead ranks
                        # restart (fresh actor, same bundle, latest
                        # checkpoint pushed); survivors never stop
                        time.sleep(failure_cfg.restart_backoff_s)
                        latest = manager.latest_checkpoint() or resume
                        replaced = executor.replace_failed_workers(latest)
                        if replaced:
                            failures_left -= 1
                            error = None
                            continue
                        # nothing replaceable (e.g. a driver-side
                        # error): fall through to the job-level ladder
                    if done:
                        break
                    time.sleep(0.25)
                # final drain (workers may already be gone on failure)
                try:
                    for rank, results in enumerate(executor.poll_results()):
                        for item in results:
                            if rank == 0:
                                history.append(item["metrics"])
                                last_metrics = item["metrics"]
                                if item["checkpoint"] is not None:
                                    manager.register(item["checkpoint"],
                                                     item["metrics"])
                except Exception:
                    pass
            except Exception as e:
                error = e
            finally:
                executor.shutdown()

            if error is None:
                return Result(metrics=last_metrics,
                              checkpoint=manager.best_checkpoint(),
                              path=exp_dir, metrics_history=history)
            if failures_left == 0:
                return Result(metrics=last_metrics,
                              checkpoint=manager.latest_checkpoint(),
                              path=exp_dir, error=error,
                              metrics_history=history)
            failures_left -= 1
            resume = manager.latest_checkpoint() or resume
            time.sleep(failure_cfg.restart_backoff_s)
