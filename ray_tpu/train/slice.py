"""TPU slice gang scheduling: reserve a whole pod slice as a unit.

A multi-host slice (e.g. v4-32 = 4 hosts) must be acquired, used, and
released as one gang: XLA collectives span every host over ICI, so a
partial slice is useless and a dead host invalidates the whole slice
(SURVEY §7.3 gang semantics). The reference expresses this with injected
custom resources (reference: python/ray/_private/accelerators/tpu.py:334
— ``TPU-{type}-head`` on worker 0 + a per-pod-name resource on every
slice host); here those resources drive a STRICT_SPREAD placement group
pinned to one slice's hosts, so the gang schedules one-worker-per-host
on a single slice or not at all.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.accelerators.tpu import _chips_per_host, slice_hosts

logger = logging.getLogger(__name__)


def slice_shape(accel_type: str) -> Tuple[int, int]:
    """(n_hosts, chips_per_host) for a topology string like 'v4-32'."""
    return slice_hosts(accel_type), _chips_per_host(accel_type)


def find_slices(nodes: List[Dict], accel_type: str) -> Dict[str, List[Dict]]:
    """pod_name -> alive member nodes, discovered from the slice resources
    the accelerator manager injects at node start."""
    pods: Dict[str, List[Dict]] = {}
    for node in nodes:
        if not node.get("alive", False):
            continue
        for res in node.get("total", {}):
            if res.startswith("tpu-slice:"):
                pods.setdefault(res, []).append(node)
    return pods


def pick_slice(nodes: List[Dict], accel_type: str,
               exclude: Optional[set] = None) -> Optional[str]:
    """Choose a healthy slice whose shape MATCHES the requested topology:
    exactly n_hosts alive members, each with the topology's chip count
    free. A larger or partially-dead slice never qualifies — ICI
    collectives need every host of the physical slice, so scheduling a
    v4-16 gang onto half a v4-32 pod would hang at initialization.
    Returns the pod resource name, or None when no whole slice is
    available."""
    n_hosts, chips = slice_shape(accel_type)
    exclude = exclude or set()
    for pod, members in sorted(find_slices(nodes, accel_type).items()):
        if pod in exclude:
            continue
        if len(members) != n_hosts:
            continue
        if any(m.get("total", {}).get("TPU", 0) != chips for m in members):
            continue
        free = [m for m in members
                if m.get("available", {}).get("TPU", 0) >= chips]
        if len(free) == n_hosts:
            return pod
    return None


def slice_bundles(pod_name: str, accel_type: str,
                  worker_resources: Optional[Dict[str, float]] = None
                  ) -> List[Dict[str, float]]:
    """One STRICT_SPREAD bundle per slice host: the pod-name resource
    pins every bundle onto this slice; TPU claims the host's chips; any
    other per-worker resources (CPU, memory, custom) ride along."""
    n_hosts, chips = slice_shape(accel_type)
    base = dict(worker_resources or {"CPU": 1.0})
    base["TPU"] = float(chips)
    base[pod_name] = 0.125
    return [dict(base) for _ in range(n_hosts)]
