from ray_tpu.tune.schedulers import (ASHAScheduler, FIFOScheduler,
                                     MedianStoppingRule,
                                     PopulationBasedTraining)
from ray_tpu.tune.search import (choice, grid_search, loguniform, randint,
                                 uniform)
from ray_tpu.tune.tuner import (ResultGrid, TrialResult, TuneConfig, Tuner,
                                get_checkpoint, report)

__all__ = ["Tuner", "TuneConfig", "ResultGrid", "TrialResult", "report",
           "get_checkpoint", "grid_search", "choice", "uniform",
           "loguniform", "randint", "ASHAScheduler", "FIFOScheduler",
           "MedianStoppingRule", "PopulationBasedTraining"]
