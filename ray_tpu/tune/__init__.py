from ray_tpu.tune.schedulers import ASHAScheduler, FIFOScheduler
from ray_tpu.tune.search import (choice, grid_search, loguniform, randint,
                                 uniform)
from ray_tpu.tune.tuner import (ResultGrid, TrialResult, TuneConfig, Tuner,
                                report)

__all__ = ["Tuner", "TuneConfig", "ResultGrid", "TrialResult", "report",
           "grid_search", "choice", "uniform", "loguniform", "randint",
           "ASHAScheduler", "FIFOScheduler"]
