"""Tuner + trial-execution controller (reference: python/ray/tune/tuner.py:44
Tuner and tune/execution/tune_controller.py:68 TuneController).

Each trial runs a function trainable inside its own actor; the controller
loop starts trials as resources allow, drains their reported results,
applies scheduler decisions (ASHA early stopping kills the trial actor),
and collects a ResultGrid. Trainables call ray_tpu.tune.report(...)."""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu

import logging

logger = logging.getLogger(__name__)
from ray_tpu.tune.schedulers import CONTINUE, EXPLOIT, STOP, FIFOScheduler
from ray_tpu.tune.search import BasicVariantGenerator

_tune_session = None


class _TuneSession:
    def __init__(self, checkpoint=None, start_iteration: int = 0):
        self.results: List[Dict] = []
        self.lock = threading.Lock()
        self.iteration = start_iteration
        self.incoming_checkpoint = checkpoint   # restore source (PBT/resume)
        self.latest_checkpoint = checkpoint

    def report(self, metrics: Dict, checkpoint=None):
        with self.lock:
            self.iteration += 1
            if checkpoint is not None:
                self.latest_checkpoint = checkpoint
            self.results.append({**metrics,
                                 "training_iteration": self.iteration})

    def drain(self):
        with self.lock:
            out = self.results
            self.results = []
            return out


def report(metrics: Optional[Dict] = None, checkpoint=None, **kwargs):
    s = _tune_session
    if s is None:
        raise RuntimeError("tune.report() called outside a trial")
    s.report({**(metrics or {}), **kwargs}, checkpoint=checkpoint)


def get_checkpoint():
    """Inside a trial: the checkpoint this trial was (re)started from —
    set when PBT exploits another trial or on restore (reference:
    ray.tune.get_checkpoint)."""
    s = _tune_session
    return s.incoming_checkpoint if s is not None else None


class TrialActor:
    """Hosts one trial; max_concurrency=2 so poll() answers during run()."""

    def __init__(self, checkpoint=None, start_iteration: int = 0):
        global _tune_session
        _tune_session = _TuneSession(checkpoint, start_iteration)
        self._session = _tune_session

    def run(self, fn, config):
        fn(config)
        return True

    def poll(self):
        return self._session.drain()

    def get_checkpoint(self):
        return self._session.latest_checkpoint


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    history: List[Dict[str, Any]]
    error: Optional[str] = None

    @property
    def last_result(self):
        return self.metrics


class ResultGrid:
    def __init__(self, results: List[TrialResult]):
        self._results = results

    def __len__(self):
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i):
        return self._results[i]

    def get_best_result(self, metric: str, mode: str = "max") -> TrialResult:
        scored = [r for r in self._results if metric in (r.metrics or {})]
        if not scored:
            raise ValueError(f"no trial reported {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else \
            min(scored, key=key)

    def get_dataframe(self):
        import pandas as pd
        return pd.DataFrame([{**r.config, **(r.metrics or {}),
                              "trial_id": r.trial_id}
                             for r in self._results])


@dataclasses.dataclass
class TuneConfig:
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    search_alg: Any = None        # sequential suggest/report (e.g. TPE)
    metric: Optional[str] = None
    mode: str = "max"
    seed: Optional[int] = None


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: Dict[str, Any],
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None,
                 resources_per_trial: Optional[Dict[str, float]] = None):
        self.trainable = trainable
        self.param_space = param_space
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config
        self.resources_per_trial = resources_per_trial or {"CPU": 1.0}

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        if tc.search_alg is not None:
            # sequential suggestion (reference: SearchAlgorithm-driven
            # trials — Optuna/HyperOpt adapters; here the native TPE):
            # configs are proposed lazily as slots free and completed
            # scores feed back into the model
            variants = None
        else:
            variants = BasicVariantGenerator(
                self.param_space, num_samples=tc.num_samples,
                seed=tc.seed).variants()
        scheduler = tc.scheduler or FIFOScheduler()
        max_conc = tc.max_concurrent_trials or max(
            1, int(ray_tpu.cluster_resources().get("CPU", 1)) - 1)

        search_metric = None
        if tc.search_alg is not None:
            search_metric = tc.metric or getattr(tc.search_alg, "metric",
                                                 None)
            if not search_metric:
                raise ValueError(
                    "search_alg requires a metric (TuneConfig.metric or "
                    "the algorithm's metric=...) — without it every "
                    "suggestion would be a blind random draw")

        def report_to_search(res: TrialResult):
            if tc.search_alg is None or res.error:
                return
            score = (res.metrics or {}).get(search_metric)
            if score is None:
                logger.warning(
                    "trial %s reported no %r metric; search model "
                    "unchanged", res.trial_id, search_metric)
                return
            tc.search_alg.report(res.config, score)

        actor_cls = ray_tpu.remote(TrialActor)
        if variants is not None:
            pending = [(f"trial_{i:05d}", cfg)
                       for i, cfg in enumerate(variants)]
            budget = 0
        else:
            pending = []
            budget = tc.num_samples       # suggestions left to draw
        running: Dict[str, Dict] = {}
        done: List[TrialResult] = []

        while pending or running or budget > 0:
            while budget > 0 and len(pending) + len(running) < max_conc:
                pending.append((f"trial_{tc.num_samples - budget:05d}",
                                tc.search_alg.suggest()))
                budget -= 1
            while pending and len(running) < max_conc:
                trial_id, cfg = pending.pop(0)
                actor = actor_cls.options(
                    max_concurrency=2,
                    resources=dict(self.resources_per_trial)).remote()
                run_ref = actor.run.remote(self.trainable, cfg)
                running[trial_id] = {"actor": actor, "config": cfg,
                                     "run_ref": run_ref, "history": [],
                                     "stopped": False}

            def restart_trial(trial_id, t, new_config, checkpoint):
                """PBT exploit: replace the trial's actor, resuming from
                `checkpoint` with the mutated config."""
                try:
                    ray_tpu.kill(t["actor"])
                except Exception:
                    pass
                it = t["history"][-1]["training_iteration"] \
                    if t["history"] else 0
                actor = actor_cls.options(
                    max_concurrency=2,
                    resources=dict(self.resources_per_trial)).remote(
                        checkpoint=checkpoint, start_iteration=it)
                t["actor"] = actor
                t["config"] = new_config
                t["run_ref"] = actor.run.remote(self.trainable, new_config)
            time.sleep(0.15)
            for trial_id, t in list(running.items()):
                try:
                    results = ray_tpu.get(t["actor"].poll.remote(),
                                          timeout=30)
                except Exception:
                    results = []
                decision = CONTINUE
                for r in results:
                    t["history"].append(r)
                    d = scheduler.on_result(trial_id, r)
                    if d == STOP:
                        decision = STOP
                    elif isinstance(d, tuple) and d and d[0] == EXPLOIT:
                        decision = d
                if (isinstance(decision, tuple) and decision[0] == EXPLOIT
                        and decision[1] in running):
                    src = running[decision[1]]
                    try:
                        ckpt = ray_tpu.get(
                            src["actor"].get_checkpoint.remote(), timeout=30)
                    except Exception:
                        ckpt = None
                    new_cfg = scheduler.explore(dict(src["config"])) \
                        if hasattr(scheduler, "explore") \
                        else dict(src["config"])
                    restart_trial(trial_id, t, new_cfg, ckpt)
                    continue
                if decision == STOP and not t["stopped"]:
                    t["stopped"] = True
                    ray_tpu.kill(t["actor"])
                    res = self._finish(trial_id, t, None)
                    report_to_search(res)
                    done.append(res)
                    del running[trial_id]
                    continue
                ready, _ = ray_tpu.wait([t["run_ref"]], timeout=0)
                if ready:
                    err = None
                    try:
                        ray_tpu.get(t["run_ref"], timeout=5)
                    except Exception as e:
                        err = str(e)
                    # final drain
                    try:
                        for r in ray_tpu.get(t["actor"].poll.remote(),
                                             timeout=10):
                            t["history"].append(r)
                    except Exception:
                        pass
                    # release the trial's CPU reservation promptly — GC of
                    # the handle would get there eventually, but later
                    # trials in this fit() need the slot now
                    try:
                        ray_tpu.kill(t["actor"])
                    except Exception:
                        pass
                    res = self._finish(trial_id, t, err)
                    report_to_search(res)
                    done.append(res)
                    del running[trial_id]
        return ResultGrid(done)

    def _finish(self, trial_id, t, err) -> TrialResult:
        hist = t["history"]
        return TrialResult(trial_id=trial_id, config=t["config"],
                           metrics=hist[-1] if hist else {},
                           history=hist, error=err)
