"""Tuner + trial-execution controller (reference: python/ray/tune/tuner.py:44
Tuner and tune/execution/tune_controller.py:68 TuneController).

Each trial runs a function trainable inside its own actor; the controller
loop starts trials as resources allow, drains their reported results,
applies scheduler decisions (ASHA early stopping kills the trial actor),
and collects a ResultGrid. Trainables call ray_tpu.tune.report(...)."""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.tune.search import BasicVariantGenerator

_tune_session = None


class _TuneSession:
    def __init__(self):
        self.results: List[Dict] = []
        self.lock = threading.Lock()
        self.iteration = 0

    def report(self, metrics: Dict):
        with self.lock:
            self.iteration += 1
            self.results.append({**metrics,
                                 "training_iteration": self.iteration})

    def drain(self):
        with self.lock:
            out = self.results
            self.results = []
            return out


def report(metrics: Optional[Dict] = None, **kwargs):
    s = _tune_session
    if s is None:
        raise RuntimeError("tune.report() called outside a trial")
    s.report({**(metrics or {}), **kwargs})


class TrialActor:
    """Hosts one trial; max_concurrency=2 so poll() answers during run()."""

    def __init__(self):
        global _tune_session
        _tune_session = _TuneSession()
        self._session = _tune_session

    def run(self, fn, config):
        fn(config)
        return True

    def poll(self):
        return self._session.drain()


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    history: List[Dict[str, Any]]
    error: Optional[str] = None

    @property
    def last_result(self):
        return self.metrics


class ResultGrid:
    def __init__(self, results: List[TrialResult]):
        self._results = results

    def __len__(self):
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i):
        return self._results[i]

    def get_best_result(self, metric: str, mode: str = "max") -> TrialResult:
        scored = [r for r in self._results if metric in (r.metrics or {})]
        if not scored:
            raise ValueError(f"no trial reported {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else \
            min(scored, key=key)

    def get_dataframe(self):
        import pandas as pd
        return pd.DataFrame([{**r.config, **(r.metrics or {}),
                              "trial_id": r.trial_id}
                             for r in self._results])


@dataclasses.dataclass
class TuneConfig:
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    metric: Optional[str] = None
    mode: str = "max"
    seed: Optional[int] = None


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: Dict[str, Any],
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None,
                 resources_per_trial: Optional[Dict[str, float]] = None):
        self.trainable = trainable
        self.param_space = param_space
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config
        self.resources_per_trial = resources_per_trial or {"CPU": 1.0}

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        variants = BasicVariantGenerator(
            self.param_space, num_samples=tc.num_samples,
            seed=tc.seed).variants()
        scheduler = tc.scheduler or FIFOScheduler()
        max_conc = tc.max_concurrent_trials or max(
            1, int(ray_tpu.cluster_resources().get("CPU", 1)) - 1)

        actor_cls = ray_tpu.remote(TrialActor)
        pending = [(f"trial_{i:05d}", cfg) for i, cfg in enumerate(variants)]
        running: Dict[str, Dict] = {}
        done: List[TrialResult] = []

        while pending or running:
            while pending and len(running) < max_conc:
                trial_id, cfg = pending.pop(0)
                actor = actor_cls.options(
                    max_concurrency=2,
                    resources=dict(self.resources_per_trial)).remote()
                run_ref = actor.run.remote(self.trainable, cfg)
                running[trial_id] = {"actor": actor, "config": cfg,
                                     "run_ref": run_ref, "history": [],
                                     "stopped": False}
            time.sleep(0.15)
            for trial_id, t in list(running.items()):
                try:
                    results = ray_tpu.get(t["actor"].poll.remote(),
                                          timeout=30)
                except Exception:
                    results = []
                decision = CONTINUE
                for r in results:
                    t["history"].append(r)
                    d = scheduler.on_result(trial_id, r)
                    if d == STOP:
                        decision = STOP
                if decision == STOP and not t["stopped"]:
                    t["stopped"] = True
                    ray_tpu.kill(t["actor"])
                    done.append(self._finish(trial_id, t, None))
                    del running[trial_id]
                    continue
                ready, _ = ray_tpu.wait([t["run_ref"]], timeout=0)
                if ready:
                    err = None
                    try:
                        ray_tpu.get(t["run_ref"], timeout=5)
                    except Exception as e:
                        err = str(e)
                    # final drain
                    try:
                        for r in ray_tpu.get(t["actor"].poll.remote(),
                                             timeout=10):
                            t["history"].append(r)
                    except Exception:
                        pass
                    done.append(self._finish(trial_id, t, err))
                    del running[trial_id]
        return ResultGrid(done)

    def _finish(self, trial_id, t, err) -> TrialResult:
        hist = t["history"]
        return TrialResult(trial_id=trial_id, config=t["config"],
                           metrics=hist[-1] if hist else {},
                           history=hist, error=err)
