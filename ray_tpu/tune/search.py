"""Search spaces + variant generation (reference:
python/ray/tune/search/variant_generator.py, sample.py — grid_search,
uniform/loguniform/choice/randint, BasicVariantGenerator)."""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math
        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, options):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def uniform(low, high):
    return Uniform(low, high)


def loguniform(low, high):
    return LogUniform(low, high)


def randint(low, high):
    return RandInt(low, high)


def choice(options):
    return Choice(options)


def grid_search(values):
    return GridSearch(values)


class BasicVariantGenerator:
    """Cross product of grid axes × num_samples random draws of the rest
    (reference: BasicVariant semantics)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grids = [self.param_space[k].values for k in grid_keys]
        out = []
        for combo in itertools.product(*grids) if grids else [()]:
            for _ in range(self.num_samples):
                cfg = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    else:
                        cfg[k] = v
                out.append(cfg)
        return out


class TPESearch:
    """Tree-structured Parzen Estimator search (reference: the Optuna /
    HyperOpt integrations in ray.tune.search — here a native, dependency-
    free TPE: observations split into good/bad by quantile; candidates
    are drawn from a Parzen model of the good points and ranked by the
    good/bad density ratio).

    Sequential interface: ``suggest()`` proposes a config, ``report()``
    feeds the observed score back.
    """

    def __init__(self, param_space: Dict[str, Any], *, metric: str = None,
                 mode: str = "min", n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min/max, got {mode!r}")
        grids = [k for k, v in param_space.items()
                 if isinstance(v, GridSearch)]
        if grids:
            raise ValueError(
                f"TPESearch does not support grid_search axes {grids}; "
                f"use tune.choice for categorical dimensions")
        self.param_space = param_space
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._obs: List[tuple] = []       # (config, score)

    # ------------------------------------------------------------ model
    def _numeric_bounds(self, dom):
        if isinstance(dom, Uniform):
            return dom.low, dom.high, False
        if isinstance(dom, LogUniform):
            return dom.lo, dom.hi, True       # log-space bounds
        if isinstance(dom, RandInt):
            return dom.low, dom.high, False
        return None

    def _to_model_space(self, dom, v):
        return math.log(v) if isinstance(dom, LogUniform) else float(v)

    def _parzen_sample(self, dom, points):
        bounds = self._numeric_bounds(dom)
        if bounds is None:                 # unknown Domain subclass
            return dom.sample(self.rng)
        lo, hi, _ = bounds
        width = (hi - lo) or 1.0
        bw = width / math.sqrt(len(points) + 1)
        center = self.rng.choice(points)
        x = self.rng.gauss(center, bw)
        x = min(max(x, lo), hi)
        if isinstance(dom, LogUniform):
            return math.exp(x)
        if isinstance(dom, RandInt):
            # randrange semantics: high is exclusive
            return min(int(round(x)), int(hi) - 1)
        return x

    def _parzen_logpdf(self, dom, points, v) -> float:
        bounds = self._numeric_bounds(dom)
        if bounds is None:
            return 0.0                     # flat contribution
        lo, hi, _ = bounds
        width = (hi - lo) or 1.0
        bw = width / math.sqrt(len(points) + 1)
        x = self._to_model_space(dom, v)
        acc = 0.0
        for p in points:
            acc += math.exp(-0.5 * ((x - p) / bw) ** 2)
        return math.log(max(acc / (len(points) * bw), 1e-300))

    def _cat_prob(self, options, counts, v) -> float:
        total = sum(counts.values()) + len(options)
        return (counts.get(v, 0) + 1) / total     # Laplace smoothing

    # -------------------------------------------------------------- api
    def suggest(self) -> Dict[str, Any]:
        domains = {k: v for k, v in self.param_space.items()
                   if isinstance(v, Domain)}
        fixed = {k: v for k, v in self.param_space.items()
                 if not isinstance(v, (Domain, GridSearch))}
        if len(self._obs) < self.n_initial or not domains:
            cfg = {k: d.sample(self.rng) for k, d in domains.items()}
            return {**fixed, **cfg}

        ordered = sorted(self._obs, key=lambda o: o[1],
                         reverse=(self.mode == "max"))
        n_good = max(1, int(len(ordered) * self.gamma))
        good = [c for c, _ in ordered[:n_good]]
        bad = [c for c, _ in ordered[n_good:]] or good

        def model_points(dom, configs, key):
            return [self._to_model_space(dom, c[key]) for c in configs]

        # per-key statistics are loop-invariant: build them once
        stats: Dict[str, tuple] = {}
        for key, dom in domains.items():
            if isinstance(dom, Choice):
                g_counts: Dict[Any, int] = {}
                b_counts: Dict[Any, int] = {}
                for c in good:
                    g_counts[c[key]] = g_counts.get(c[key], 0) + 1
                for c in bad:
                    b_counts[c[key]] = b_counts.get(c[key], 0) + 1
                weights = [self._cat_prob(dom.options, g_counts, o)
                           for o in dom.options]
                stats[key] = (g_counts, b_counts, weights)
            else:
                stats[key] = (model_points(dom, good, key),
                              model_points(dom, bad, key))

        best_cfg, best_score = None, -math.inf
        for _ in range(self.n_candidates):
            cand = dict(fixed)
            score = 0.0
            for key, dom in domains.items():
                if isinstance(dom, Choice):
                    g_counts, b_counts, weights = stats[key]
                    v = self.rng.choices(dom.options, weights=weights)[0]
                    score += math.log(
                        self._cat_prob(dom.options, g_counts, v)) \
                        - math.log(
                            self._cat_prob(dom.options, b_counts, v))
                else:
                    gp, bp = stats[key]
                    v = self._parzen_sample(dom, gp)
                    score += self._parzen_logpdf(dom, gp, v) \
                        - self._parzen_logpdf(dom, bp, v)
                cand[key] = v
            if score > best_score:
                best_cfg, best_score = cand, score
        return best_cfg

    def report(self, config: Dict[str, Any], score: float) -> None:
        if score is None or not isinstance(score, (int, float)) \
                or score != score:
            return
        self._obs.append((dict(config), float(score)))
