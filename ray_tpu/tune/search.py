"""Search spaces + variant generation (reference:
python/ray/tune/search/variant_generator.py, sample.py — grid_search,
uniform/loguniform/choice/randint, BasicVariantGenerator)."""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math
        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, options):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def uniform(low, high):
    return Uniform(low, high)


def loguniform(low, high):
    return LogUniform(low, high)


def randint(low, high):
    return RandInt(low, high)


def choice(options):
    return Choice(options)


def grid_search(values):
    return GridSearch(values)


class BasicVariantGenerator:
    """Cross product of grid axes × num_samples random draws of the rest
    (reference: BasicVariant semantics)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grids = [self.param_space[k].values for k in grid_keys]
        out = []
        for combo in itertools.product(*grids) if grids else [()]:
            for _ in range(self.num_samples):
                cfg = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    else:
                        cfg[k] = v
                out.append(cfg)
        return out
