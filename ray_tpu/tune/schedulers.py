"""Trial schedulers (reference: python/ray/tune/schedulers/ —
FIFOScheduler, ASHA async_hyperband.py). Decisions are made per reported
result: CONTINUE or STOP."""

from __future__ import annotations

import collections
from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE


class ASHAScheduler:
    """Async successive halving: at each rung (grace_period * rf^k steps),
    a trial continues only if it's in the top 1/reduction_factor of
    completed rung entries (reference: schedulers/async_hyperband.py)."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.rung_records: Dict[int, List[float]] = \
            collections.defaultdict(list)
        self._evaluated: Dict[str, set] = collections.defaultdict(set)

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        score = result.get(self.metric)
        if t is None or score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        # evaluate at the first result AT OR PAST each rung (trials may
        # report on a stride that skips the exact rung value)
        for rung in self.rungs:
            if t >= rung and rung not in self._evaluated[trial_id]:
                self._evaluated[trial_id].add(rung)
                sign = 1.0 if self.mode == "max" else -1.0
                rec = self.rung_records[rung]
                rec.append(sign * score)
                rec.sort(reverse=True)
                k = max(1, len(rec) // self.rf)
                if sign * score < rec[k - 1]:
                    return STOP
        return CONTINUE
