"""Trial schedulers (reference: python/ray/tune/schedulers/ —
FIFOScheduler, ASHA async_hyperband.py). Decisions are made per reported
result: CONTINUE or STOP."""

from __future__ import annotations

import collections
from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"     # returned as ("EXPLOIT", source_trial_id)


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE


class ASHAScheduler:
    """Async successive halving: at each rung (grace_period * rf^k steps),
    a trial continues only if it's in the top 1/reduction_factor of
    completed rung entries (reference: schedulers/async_hyperband.py)."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.rung_records: Dict[int, List[float]] = \
            collections.defaultdict(list)
        self._evaluated: Dict[str, set] = collections.defaultdict(set)

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        score = result.get(self.metric)
        if t is None or score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        # evaluate at the first result AT OR PAST each rung (trials may
        # report on a stride that skips the exact rung value)
        for rung in self.rungs:
            if t >= rung and rung not in self._evaluated[trial_id]:
                self._evaluated[trial_id].add(rung)
                sign = 1.0 if self.mode == "max" else -1.0
                rec = self.rung_records[rung]
                rec.append(sign * score)
                rec.sort(reverse=True)
                k = max(1, len(rec) // self.rf)
                if sign * score < rec[k - 1]:
                    return STOP
        return CONTINUE


class MedianStoppingRule:
    """Stop a trial whose running-average score falls below the median of
    the other trials' running averages at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 3, min_samples_required: int = 3):
        self.metric = metric
        self.sign = 1.0 if mode == "max" else -1.0
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._sums: Dict[str, float] = collections.defaultdict(float)
        self._counts: Dict[str, int] = collections.defaultdict(int)

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        score = result.get(self.metric)
        if t is None or score is None:
            return CONTINUE
        self._sums[trial_id] += self.sign * score
        self._counts[trial_id] += 1
        if t < self.grace:
            return CONTINUE
        others = [self._sums[k] / self._counts[k]
                  for k in self._sums if k != trial_id]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        mine = self._sums[trial_id] / self._counts[trial_id]
        return STOP if mine < median else CONTINUE


class PopulationBasedTraining:
    """PBT (reference: schedulers/pbt.py): every perturbation_interval
    steps a bottom-quantile trial exploits a top-quantile trial — the
    controller restarts it from the source's checkpoint with a mutated
    copy of the source's config (explore)."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Dict = None,
                 quantile_fraction: float = 0.25, seed: int = 0):
        import random
        self.metric = metric
        self.sign = 1.0 if mode == "max" else -1.0
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._rng = random.Random(seed)
        self._scores: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = collections.defaultdict(int)

    def on_result(self, trial_id: str, result: Dict):
        t = result.get(self.time_attr)
        score = result.get(self.metric)
        if t is None or score is None:
            return CONTINUE
        self._scores[trial_id] = self.sign * score
        if t - self._last_perturb[trial_id] < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        pop = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(pop)
        if n < 4:
            return CONTINUE
        k = max(1, int(n * self.quantile))
        bottom = [tid for tid, _ in pop[:k]]
        top = [tid for tid, _ in pop[-k:]]
        if trial_id in bottom:
            src = self._rng.choice(top)
            if src != trial_id:
                return (EXPLOIT, src)
        return CONTINUE

    def explore(self, config: Dict) -> Dict:
        """Mutate a copied config: resample (0.25) or scale by 0.8/1.2."""
        out = dict(config)
        for key, spec in self.mutations.items():
            if key not in out:
                continue
            if self._rng.random() < 0.25:
                if callable(spec):
                    out[key] = spec()
                elif isinstance(spec, (list, tuple)):
                    out[key] = self._rng.choice(list(spec))
                elif hasattr(spec, "sample"):
                    out[key] = spec.sample(self._rng)
            else:
                factor = self._rng.choice([0.8, 1.2])
                if isinstance(out[key], (int, float)):
                    out[key] = type(out[key])(out[key] * factor)
        return out
