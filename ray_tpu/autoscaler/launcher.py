"""Cluster launcher: bring a cluster up/down from a YAML spec
(reference: `ray up` — python/ray/autoscaler/_private/commands.py with
schema ray-schema.json; the v2 instance-manager reconciler supplies the
runtime scaling here via autoscaler.Autoscaler).

Schema (YAML):

    cluster_name: my-cluster
    provider:
      type: fake | gcp_tpu
      # gcp_tpu only:
      project: my-project
      zone: us-central2-b
    head:
      num_cpus: 4
      resources: {}           # extra custom resources
    available_node_types:
      cpu_worker:
        resources: {CPU: 4}
        min_workers: 0
        max_workers: 10
      v5e_16:
        resources: {TPU: 4}
        tpu_accelerator_type: v5litepod-16   # slice type (gcp_tpu)
        min_workers: 0
        max_workers: 4
    idle_timeout_s: 60

`up()` starts the head in-process, pre-launches every type's min_workers
through the provider, and runs the demand-driven reconciler on a
background thread. `down()` terminates provider nodes and the head.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.autoscaler.autoscaler import (Autoscaler, AutoscalerConfig,
                                           NodeTypeConfig)
from ray_tpu.autoscaler.node_provider import (FakeMultiNodeProvider,
                                              GcpTpuNodeProvider)

logger = logging.getLogger(__name__)

STATE_FILE = "/tmp/raytpu/cluster_launcher.json"


def load_config(path: str) -> Dict[str, Any]:
    import yaml
    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict):
        raise ValueError(f"{path}: expected a mapping at top level")
    cfg.setdefault("cluster_name", "ray-tpu")
    cfg.setdefault("head", {})
    cfg.setdefault("available_node_types", {})
    prov = cfg.get("provider") or {}
    if prov.get("type") not in ("fake", "gcp_tpu"):
        raise ValueError("provider.type must be 'fake' or 'gcp_tpu'")
    for name, nt in cfg["available_node_types"].items():
        if "resources" not in nt:
            raise ValueError(f"node type {name!r} needs `resources`")
        nt.setdefault("min_workers", 0)
        nt.setdefault("max_workers", 10)
        nt.setdefault("labels", {})
    return cfg


def _make_provider(cfg: Dict, gcs_address: str, detached: bool = False):
    prov = cfg["provider"]
    if prov["type"] == "fake":
        return FakeMultiNodeProvider(gcs_address,
                                     session_name=cfg["cluster_name"],
                                     detached=detached)
    kw = {}
    types = cfg["available_node_types"]
    slice_types = [nt.get("tpu_accelerator_type")
                   for nt in types.values() if nt.get("tpu_accelerator_type")]
    if slice_types:
        kw["accelerator_type"] = slice_types[0]
    if prov.get("runtime_version"):
        kw["runtime_version"] = prov["runtime_version"]
    return GcpTpuNodeProvider(project=prov["project"], zone=prov["zone"],
                              cluster_address=gcs_address, **kw)


class ClusterHandle:
    """A launched cluster: head node + provider + reconciler thread."""

    def __init__(self, config: Dict, head, provider,
                 autoscaler: Optional[Autoscaler], stop: threading.Event,
                 thread: Optional[threading.Thread]):
        self.config = config
        self.head = head
        self.provider = provider
        self.autoscaler = autoscaler
        self._stop = stop
        self._thread = thread

    @property
    def gcs_address(self) -> str:
        return self.head.gcs_address

    def down(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        for pid in list(self.provider.non_terminated_nodes()):
            try:
                self.provider.terminate_node(pid)
            except Exception:
                logger.exception("terminate %s failed", pid)
        self.head.kill()
        try:
            os.unlink(STATE_FILE)
        except OSError:
            pass


def up(config_path: str, start_autoscaler: bool = True,
       detached: bool = False) -> ClusterHandle:
    """Bring the cluster up: head + min_workers + reconciler.

    detached=True (the `ray_tpu up` CLI) lets the cluster outlive this
    process; the default ties daemon lifetimes to the caller via
    PDEATHSIG, which is what tests want."""
    from ray_tpu._private import node as node_mod

    cfg = load_config(config_path)
    head_cfg = cfg["head"]
    head = node_mod.start_head(
        num_cpus=head_cfg.get("num_cpus", 1),
        resources=dict(head_cfg.get("resources") or {}),
        detached=detached)
    provider = _make_provider(cfg, head.gcs_address, detached=detached)
    for name, nt in cfg["available_node_types"].items():
        for _ in range(int(nt["min_workers"])):
            provider.create_node(name, dict(nt["resources"]),
                                 dict(nt["labels"]))

    stop = threading.Event()
    thread = None
    asc = None
    if start_autoscaler:
        def nodes_fn(addr=head.gcs_address):
            # standalone GCS query: the launcher process need not be a
            # ray_tpu driver
            import asyncio

            from ray_tpu._private import rpc

            async def go():
                conn = await rpc.connect(addr, name="launcher", retries=3)
                try:
                    return await conn.call("get_all_nodes")
                finally:
                    await conn.close()
            return asyncio.run(go())

        asc = Autoscaler(
            AutoscalerConfig(
                node_types={
                    name: NodeTypeConfig(resources=dict(nt["resources"]),
                                         max_workers=int(nt["max_workers"]),
                                         labels=dict(nt["labels"]))
                    for name, nt in cfg["available_node_types"].items()},
                idle_timeout_s=float(cfg.get("idle_timeout_s", 60.0))),
            provider, protected_node_ids=[head.node_id],
            nodes_fn=nodes_fn)
        thread = threading.Thread(target=asc.run, args=(stop,),
                                  name="cluster-autoscaler", daemon=True)
        thread.start()

    os.makedirs(os.path.dirname(STATE_FILE), exist_ok=True)
    with open(STATE_FILE, "w") as f:
        json.dump({"cluster_name": cfg["cluster_name"],
                   "gcs_address": head.gcs_address,
                   "provider": cfg["provider"],
                   "config_path": os.path.abspath(config_path),
                   "started_at": time.time()}, f)
    logger.info("cluster %s up: GCS %s, %d node type(s)",
                cfg["cluster_name"], head.gcs_address,
                len(cfg["available_node_types"]))
    return ClusterHandle(cfg, head, provider, asc, stop, thread)


def down_from_state() -> bool:
    """`ray_tpu down` from a different process than `up`: terminate cloud
    nodes via a re-instantiated provider, then sweep local processes."""
    try:
        with open(STATE_FILE) as f:
            st = json.load(f)
    except OSError:
        return False
    prov = st.get("provider") or {}
    if prov.get("type") == "gcp_tpu":
        try:
            p = GcpTpuNodeProvider(project=prov["project"],
                                   zone=prov["zone"],
                                   cluster_address=st["gcs_address"])
            for pid in p.non_terminated_nodes():
                p.terminate_node(pid)
        except Exception:
            logger.exception("cloud teardown failed; nodes may remain")
    try:
        os.unlink(STATE_FILE)
    except OSError:
        pass
    return True
