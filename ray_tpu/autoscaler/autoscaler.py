"""Autoscaler: reconcile cluster size against pending resource demand
(reference: the v2 architecture — python/ray/autoscaler/v2/autoscaler.py:42,
instance_manager, scheduler.py binpacking against ClusterResourceState).
Demand comes from node-manager heartbeats (queued lease requests) through
the GCS node table; the provider launches/terminates nodes."""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional

from ray_tpu._private import scheduling

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class NodeTypeConfig:
    resources: Dict[str, float]
    max_workers: int = 10
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig]
    idle_timeout_s: float = 30.0
    upscale_interval_s: float = 2.0
    # consecutive step() failures back the loop off exponentially up to
    # this cap (a dead cloud API must not be hammered — nor fill the log
    # — every upscale_interval_s)
    max_backoff_s: float = 60.0
    # fold the serve controller's unmet replica demand
    # (ServeController.get_replica_demand) into binpacking, so the
    # provider acquires TPU slices for replicas the serve control loop
    # wants before their lease requests even reach a node manager. The
    # controller keeps these rows honest for the fleet plane: a
    # deployment shedding burn overflow to its fallback_model, or one
    # scaled to zero, bids for no slices (serve/fleet.py)
    serve_demand: bool = True


class Autoscaler:
    def __init__(self, config: AutoscalerConfig, provider,
                 protected_node_ids: Optional[List[str]] = None,
                 nodes_fn=None, serve_demand_fn=None):
        self.config = config
        self.provider = provider
        self.protected = set(protected_node_ids or [])
        self._nodes_fn = nodes_fn    # None -> the driver's node table
        self._launched: Dict[str, str] = {}   # node_id -> node_type
        # launched but not yet registered in the node table; counted as
        # capacity during binpacking so a slow-booting node (minutes for a
        # TPU-VM) isn't re-launched every step for the same demand
        self._inflight: Dict[str, str] = {}   # node_id -> node_type
        self._idle_since: Dict[str, float] = {}
        # serve demand source: injected fn for tests, else lazily
        # discovered SERVE_CONTROLLER actor (absent = no serve = [])
        self._serve_demand_fn = serve_demand_fn
        self._serve_ctrl = None
        self._serve_ctrl_next_probe = 0.0
        self._consecutive_failures = 0
        from ray_tpu.util.metrics import Counter
        self._step_failures = Counter(
            "autoscaler_step_failures",
            "autoscaler reconcile steps that raised (provider/API "
            "errors); the run loop backs off exponentially while these "
            "accumulate")

    def _cluster_nodes(self) -> List[Dict]:
        if self._nodes_fn is not None:
            return self._nodes_fn()
        import ray_tpu
        return ray_tpu.nodes()

    def _serve_demand(self) -> List[Dict[str, float]]:
        """Replica demand exported by the serve control loop (ROADMAP
        item 2: the burn-rate autoscaler raises targets, THIS is how
        those targets turn into TPU slices). Best-effort: no controller
        (or a dead one) means no serve demand, never a failed step."""
        if not self.config.serve_demand:
            return []
        if self._serve_demand_fn is not None:
            try:
                return list(self._serve_demand_fn() or [])
            except Exception:
                return []
        import ray_tpu
        now = time.monotonic()
        if self._serve_ctrl is None:
            if now < self._serve_ctrl_next_probe:
                return []
            try:
                self._serve_ctrl = ray_tpu.get_actor(
                    "SERVE_CONTROLLER", namespace="serve")
            except Exception:
                # no serve session yet; re-probe at a gentle cadence
                self._serve_ctrl_next_probe = now + 10.0
                return []
        try:
            return list(ray_tpu.get(
                self._serve_ctrl.get_replica_demand.remote(),
                timeout=5) or [])
        except Exception:
            self._serve_ctrl = None   # controller died/rolled: rediscover
            return []

    def step(self) -> Dict:
        """One reconcile iteration; returns a summary of actions."""
        nodes = self._cluster_nodes()
        alive = [n for n in nodes if n["alive"]]
        demand: List[Dict[str, float]] = []
        for n in alive:
            demand.extend(n.get("pending_demand") or [])
        # serve demand dedupes against lease demand: once a wanted
        # replica's actor lease is queued at a node manager it shows up
        # in pending_demand with the same resource shape — counting both
        # would double-launch
        serve_rows = self._serve_demand()
        if serve_rows:
            queued: Dict[tuple, int] = {}
            for req in demand:
                k = tuple(sorted(req.items()))
                queued[k] = queued.get(k, 0) + 1
            for req in serve_rows:
                k = tuple(sorted(req.items()))
                if queued.get(k, 0) > 0:
                    queued[k] -= 1
                else:
                    demand.append(req)
        actions = {"launched": [], "terminated": []}

        # reconcile in-flight launches: once a launched node registers it
        # counts through the real node table instead. Slice providers
        # (GCE queued resources) name a whole slice; its hosts register
        # with their own node ids but advertise tpu-slice:{provider_id},
        # which is how provider ids map back to cluster nodes.
        alive_ids = {n["node_id"] for n in alive}
        slice_of = {}                    # provider_id -> [cluster node]
        for n in alive:
            for res in n.get("total", {}):
                if res.startswith("tpu-slice:"):
                    slice_of.setdefault(res[len("tpu-slice:"):],
                                        []).append(n)
        for nid in list(self._inflight):
            if nid in alive_ids or nid in slice_of:
                del self._inflight[nid]
        # drop launches the provider declared dead (FAILED queued
        # resources etc.) so the demand can relaunch
        try:
            live_provider = set(self.provider.non_terminated_nodes())
        except Exception:
            live_provider = None
        if live_provider is not None:
            for nid in list(self._inflight):
                if nid not in live_provider:
                    self._inflight.pop(nid, None)
                    self._launched.pop(nid, None)

        # --- scale up: binpack unmet demand onto live + in-flight +
        # hypothetical new nodes (one launch can absorb many requests)
        if demand:
            shadow = {n["node_id"]: {"total": dict(n["total"]),
                                     "available": dict(n["available"]),
                                     "alive": True}
                      for n in alive}
            for nid, tname in self._inflight.items():
                res = dict(self.config.node_types[tname].resources)
                shadow[nid] = {"total": dict(res), "available": res,
                               "alive": True}
            per_type_count: Dict[str, int] = {}
            for tname in self._launched.values():
                per_type_count[tname] = per_type_count.get(tname, 0) + 1
            for req in demand:
                nid = scheduling.hybrid_policy(shadow, req)
                if nid is not None:
                    scheduling.subtract(shadow[nid]["available"], req)
                    continue
                for tname, tcfg in self.config.node_types.items():
                    if per_type_count.get(tname, 0) >= tcfg.max_workers:
                        continue
                    if scheduling.feasible(tcfg.resources, req):
                        nid = self.provider.create_node(
                            tname, tcfg.resources, tcfg.labels)
                        self._launched[nid] = tname
                        self._inflight[nid] = tname
                        per_type_count[tname] = \
                            per_type_count.get(tname, 0) + 1
                        actions["launched"].append(tname)
                        res = dict(tcfg.resources)
                        scheduling.subtract(res, req)
                        shadow[nid] = {"total": dict(tcfg.resources),
                                       "available": res, "alive": True}
                        break

        # --- scale down: terminate launched nodes idle past the timeout
        now = time.monotonic()
        for n in alive:
            nid = n["node_id"]
            # slice hosts terminate at slice granularity via provider id
            provider_id = nid
            if nid not in self._launched:
                provider_id = next(
                    (pid for pid, members in slice_of.items()
                     if pid in self._launched
                     and any(m["node_id"] == nid for m in members)), None)
                if provider_id is None:
                    continue
            if nid in self.protected:
                continue
            busy = any(n["available"].get(k, 0) < n["total"].get(k, 0) - 1e-9
                       for k in n["total"]
                       if k != "object_store_memory")
            if busy or (n.get("pending_demand") or []):
                self._idle_since.pop(nid, None)
                continue
            first_idle = self._idle_since.setdefault(nid, now)
            if now - first_idle > self.config.idle_timeout_s:
                # a slice only terminates when EVERY member host is idle
                if provider_id != nid:
                    members = slice_of.get(provider_id, [])
                    if not all(
                            now - self._idle_since.get(m["node_id"], now)
                            > self.config.idle_timeout_s
                            for m in members):
                        continue
                try:
                    self.provider.terminate_node(provider_id)
                except Exception:
                    logger.exception("terminate %s failed; will retry",
                                     provider_id)
                    continue
                self._launched.pop(provider_id, None)
                # drop idle state for EVERY member of a terminated slice,
                # not just the triggering host (stale entries would
                # otherwise accumulate for the life of the reconciler)
                if provider_id != nid:
                    for m in slice_of.get(provider_id, []):
                        self._idle_since.pop(m["node_id"], None)
                self._idle_since.pop(nid, None)
                actions["terminated"].append(provider_id)
        # prune idle entries for nodes no longer alive (dead or terminated
        # out-of-band): _idle_since must not grow without bound
        alive_ids = {n["node_id"] for n in alive}
        for nid in [k for k in self._idle_since if k not in alive_ids]:
            self._idle_since.pop(nid, None)
        return actions

    def _step_delay(self, failures: int) -> float:
        """Loop cadence: the configured interval while healthy, doubling
        per consecutive failure up to max_backoff_s — a dead provider
        API is retried at a polite pace instead of hot-looping a full
        stack trace every interval."""
        base = self.config.upscale_interval_s
        if failures <= 0:
            return base
        return min(self.config.max_backoff_s,
                   base * (2.0 ** min(failures, 6)))

    def run(self, stop_event=None):
        while stop_event is None or not stop_event.is_set():
            try:
                self.step()
                self._consecutive_failures = 0
            except Exception:
                self._consecutive_failures += 1
                self._step_failures.inc()
                if self._consecutive_failures == 1:
                    logger.exception("autoscaler step failed")
                else:
                    # the first failure carried the stack; repeats log
                    # one line with the escalating backoff
                    logger.warning(
                        "autoscaler step failed (%d consecutive); "
                        "backing off %.1fs",
                        self._consecutive_failures,
                        self._step_delay(self._consecutive_failures))
            delay = self._step_delay(self._consecutive_failures)
            if stop_event is not None:
                if stop_event.wait(delay):
                    return
            else:
                time.sleep(delay)
