from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
from ray_tpu.autoscaler.node_provider import (FakeMultiNodeProvider,
                                              NodeProvider)

__all__ = ["Autoscaler", "AutoscalerConfig", "NodeProvider",
           "FakeMultiNodeProvider"]
