"""Cloud node providers (reference: python/ray/autoscaler/node_provider.py
ABC + _private/fake_multi_node/node_provider.py:236 FakeMultiNodeProvider).
The fake provider launches REAL node-manager processes locally so the whole
autoscaler loop is testable hermetically — same trick as the reference."""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional


class NodeProvider:
    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Launches local node managers against the current GCS."""

    def __init__(self, gcs_address: str, session_name: str = "fake",
                 detached: bool = False):
        self.gcs_address = gcs_address
        self.session_name = session_name
        self.detached = detached
        self.nodes: Dict[str, object] = {}

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        from ray_tpu._private import node as node_mod
        res = dict(resources)
        num_cpus = res.pop("CPU", 1)
        ln = node_mod.start_node(
            self.gcs_address, num_cpus=num_cpus, resources=res,
            labels={**labels, "node_type": node_type},
            session_name=self.session_name,
            object_store_memory=64 * 1024 * 1024,
            detached=self.detached)
        self.nodes[ln.node_id] = ln
        return ln.node_id

    def terminate_node(self, provider_node_id: str) -> None:
        ln = self.nodes.pop(provider_node_id, None)
        if ln is not None:
            ln.kill()

    def non_terminated_nodes(self) -> List[str]:
        return list(self.nodes)


class GceVmNodeProvider(NodeProvider):
    """Plain GCE CPU VM provider (head / non-accelerator workers) over
    the Compute Engine instances API (reference:
    python/ray/autoscaler/_private/gcp/node_provider.py — the non-TPU
    half of the GCP integration). Same injectable-transport pattern as
    GcpTpuNodeProvider: ``api(method, path, body) -> dict`` so the state
    machine tests hermetically; the default transport talks to
    compute.googleapis.com with a metadata-server token."""

    _LIVE_STATES = ("PROVISIONING", "STAGING", "RUNNING", "REPAIRING")

    def __init__(self, project: str, zone: str, cluster_address: str,
                 machine_type: str = "n2-standard-8",
                 image: str = ("projects/debian-cloud/global/images/"
                               "family/debian-12"),
                 disk_gb: int = 100, api=None):
        self.project = project
        self.zone = zone
        self.cluster_address = cluster_address
        self.machine_type = machine_type
        self.image = image
        self.disk_gb = disk_gb
        self.api = api or self._default_api
        self.created: Dict[str, str] = {}    # name -> node_type
        self._token = None
        self._token_expiry = 0.0

    def _default_api(self, method: str, path: str, body=None):
        import json
        import time
        import urllib.request
        if self._token is None or time.monotonic() > self._token_expiry:
            self._token = GcpTpuNodeProvider._metadata_token()
            self._token_expiry = time.monotonic() + 45 * 60
        url = f"https://compute.googleapis.com/compute/v1/{path}"
        req = urllib.request.Request(
            url, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Authorization": f"Bearer {self._token}",
                     "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read() or b"{}")

    def _parent(self) -> str:
        return f"projects/{self.project}/zones/{self.zone}"

    def _startup_script(self, name: str) -> str:
        # the provider-id label is how the instance manager matches the
        # registered cluster node back to this VM (instance_manager
        # _match_ray_nodes reads node labels)
        return ("#!/bin/bash\n"
                "python -m ray_tpu.scripts.cli start "
                f"--address {self.cluster_address} "
                f"--labels '{{\"ray-tpu-provider-id\": \"{name}\"}}'\n")

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        name = (f"rt-{GcpTpuNodeProvider._sanitize(node_type)}-"
                f"{uuid.uuid4().hex[:8]}")
        body = {
            "name": name,
            "machineType": (f"zones/{self.zone}/machineTypes/"
                            f"{self.machine_type}"),
            "disks": [{"boot": True, "autoDelete": True,
                       "initializeParams": {
                           "sourceImage": self.image,
                           "diskSizeGb": str(self.disk_gb)}}],
            "networkInterfaces": [{"network": "global/networks/default"}],
            "metadata": {"items": [
                {"key": "startup-script",
                 "value": self._startup_script(name)}]},
            "labels": {
                **{GcpTpuNodeProvider._sanitize(k):
                   GcpTpuNodeProvider._sanitize(str(v))
                   for k, v in labels.items()},
                "ray-tpu-node-type": GcpTpuNodeProvider._sanitize(
                    node_type)},
        }
        self.api("POST", f"{self._parent()}/instances", body)
        self.created[name] = node_type
        return name

    def terminate_node(self, provider_node_id: str) -> None:
        self.api("DELETE",
                 f"{self._parent()}/instances/{provider_node_id}")
        self.created.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> List[str]:
        out = []
        token = None
        while True:
            path = (f"{self._parent()}/instances"
                    "?filter=labels.ray-tpu-node-type:*")
            if token:
                path += f"&pageToken={token}"
            try:
                info = self.api("GET", path)
            except Exception:
                return list(self.created)   # transient outage: last known
            for inst in info.get("items", []) or []:
                if inst.get("status") in self._LIVE_STATES:
                    out.append(inst["name"])
            # paginate: truncating at one page (500 VMs) would make the
            # instance manager mark live instances vanished and relaunch
            token = info.get("nextPageToken")
            if not token:
                return out


class GcpTpuNodeProvider(NodeProvider):
    """GCE TPU-VM provider over the Cloud TPU queued-resources API
    (reference: python/ray/autoscaler/_private/gcp/ + the v2 instance
    manager's cloud abstraction; queued resources are how real TPU pods
    are obtained — capacity requests queue until a whole slice frees up,
    which is exactly the gang semantics train/slice.py expects).

    One provider "node" == one TPU slice (all its hosts): the startup
    script joins every slice host to the cluster, where the accelerator
    manager injects the tpu-slice:{name} resources. ``api`` is the
    injectable transport (method, path, body) -> dict so the full state
    machine is testable without credentials or egress; the default
    transport talks to tpu.googleapis.com with a metadata-server token.
    """

    # queued-resource states, per the Cloud TPU API
    _PENDING = ("ACCEPTED", "WAITING_FOR_RESOURCES", "PROVISIONING",
                "CREATING")
    _DEAD = ("FAILED", "SUSPENDED", "SUSPENDING", "DELETING")

    def __init__(self, project: str, zone: str, cluster_address: str,
                 accelerator_type: str = "v5litepod-16",
                 runtime_version: str = "v2-alpha-tpuv5-lite",
                 api=None):
        self.project = project
        self.zone = zone
        self.cluster_address = cluster_address
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.api = api or self._default_api
        self.queued: Dict[str, Dict] = {}   # qr name -> last known info
        self._token: Optional[str] = None
        self._token_expiry = 0.0

    @staticmethod
    def _sanitize(name: str) -> str:
        """GCE resource names: lowercase letters, digits, hyphens."""
        import re
        out = re.sub(r"[^a-z0-9-]", "-", name.lower())
        return out.strip("-") or "node"

    # ------------------------------------------------------------ transport
    def _default_api(self, method: str, path: str, body=None):
        import json
        import time
        import urllib.request
        if self._token is None or time.monotonic() > self._token_expiry:
            self._token = self._metadata_token()
            self._token_expiry = time.monotonic() + 45 * 60
        token = self._token
        url = f"https://tpu.googleapis.com/v2alpha1/{path}"
        req = urllib.request.Request(
            url, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Authorization": f"Bearer {token}",
                     "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read() or b"{}")

    @staticmethod
    def _metadata_token() -> str:
        import json
        import urllib.request
        req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/instance/"
            "service-accounts/default/token",
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())["access_token"]

    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    # ------------------------------------------------------------- lifecycle
    def _startup_script(self, pod_name: str) -> str:
        return (
            "#!/bin/bash\n"
            f"export TPU_NAME={pod_name}\n"
            "python -m ray_tpu.scripts.cli start "
            f"--address {self.cluster_address}\n")

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        """Submit a queued-resource request for one whole slice. The
        request may sit in WAITING_FOR_RESOURCES for a long time — that
        pending state is surfaced through non_terminated_nodes so the
        autoscaler counts it as in-flight capacity instead of re-asking."""
        name = f"rt-{self._sanitize(node_type)}-{uuid.uuid4().hex[:8]}"
        body = {
            "tpu": {"nodeSpec": [{
                "parent": self._parent(),
                "nodeId": name,
                "node": {
                    "acceleratorType": self.accelerator_type,
                    "runtimeVersion": self.runtime_version,
                    "metadata": {
                        "startup-script": self._startup_script(name)},
                    "labels": {
                        **{self._sanitize(k): self._sanitize(str(v))
                           for k, v in labels.items()},
                        "ray-tpu-node-type": self._sanitize(node_type)},
                },
            }]},
            "queueingPolicy": {},
        }
        self.api("POST",
                 f"{self._parent()}/queuedResources?queuedResourceId={name}",
                 body)
        self.queued[name] = {"state": "ACCEPTED", "node_type": node_type}
        return name

    def _refresh_all(self) -> None:
        """One LIST call refreshes every tracked queued resource (the
        reconcile loop runs every couple of seconds; per-QR GETs would be
        N sequential round trips). A QR missing from the listing was
        deleted out of band -> dead."""
        try:
            info = self.api("GET", f"{self._parent()}/queuedResources")
        except Exception:
            return   # transient outage: keep last known states
        listed = {}
        for qr in info.get("queuedResources", []) or []:
            name = (qr.get("name") or "").rsplit("/", 1)[-1]
            listed[name] = (qr.get("state") or {}).get("state", "UNKNOWN")
        for name in list(self.queued):
            if name in listed:
                self.queued[name]["state"] = listed[name]
            else:
                self.queued[name]["state"] = "FAILED"   # gone server-side

    def terminate_node(self, provider_node_id: str) -> None:
        """Forget the node only when the cloud acknowledged the delete —
        otherwise a transient API error would orphan a live, billing
        slice that nothing retries."""
        self.api("DELETE",
                 f"{self._parent()}/queuedResources/"
                 f"{provider_node_id}?force=true")
        self.queued.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> List[str]:
        self._refresh_all()
        out = []
        for name in list(self.queued):
            state = self.queued[name].get("state", "UNKNOWN")
            if state in self._DEAD:
                # terminal queued resources must be deleted server-side
                # (the API keeps them until explicit deletion)
                try:
                    self.api("DELETE",
                             f"{self._parent()}/queuedResources/"
                             f"{name}?force=true")
                except Exception:
                    pass
                self.queued.pop(name, None)
            else:
                out.append(name)
        return out

    def pending_nodes(self) -> List[str]:
        """Requests still queueing/provisioning (ACTIVE slices have
        already joined the cluster through their startup scripts)."""
        return [n for n, info in self.queued.items()
                if info.get("state") in self._PENDING]
