"""Cloud node providers (reference: python/ray/autoscaler/node_provider.py
ABC + _private/fake_multi_node/node_provider.py:236 FakeMultiNodeProvider).
The fake provider launches REAL node-manager processes locally so the whole
autoscaler loop is testable hermetically — same trick as the reference."""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional


class NodeProvider:
    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Launches local node managers against the current GCS."""

    def __init__(self, gcs_address: str, session_name: str = "fake"):
        self.gcs_address = gcs_address
        self.session_name = session_name
        self.nodes: Dict[str, object] = {}

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        from ray_tpu._private import node as node_mod
        res = dict(resources)
        num_cpus = res.pop("CPU", 1)
        ln = node_mod.start_node(
            self.gcs_address, num_cpus=num_cpus, resources=res,
            labels={**labels, "node_type": node_type},
            session_name=self.session_name,
            object_store_memory=64 * 1024 * 1024)
        self.nodes[ln.node_id] = ln
        return ln.node_id

    def terminate_node(self, provider_node_id: str) -> None:
        ln = self.nodes.pop(provider_node_id, None)
        if ln is not None:
            ln.kill()

    def non_terminated_nodes(self) -> List[str]:
        return list(self.nodes)


class GcpTpuNodeProvider(NodeProvider):
    """GCE TPU-VM provider skeleton (queued-resources aware). Requires
    cloud credentials + network egress; methods raise until configured
    (reference: python/ray/autoscaler/_private/gcp/)."""

    def __init__(self, project: str, zone: str):
        self.project = project
        self.zone = zone

    def create_node(self, node_type, resources, labels):
        raise NotImplementedError(
            "GCE TPU provider requires gcloud credentials; use "
            "FakeMultiNodeProvider for local clusters")

    def terminate_node(self, provider_node_id):
        raise NotImplementedError

    def non_terminated_nodes(self):
        return []
