"""Autoscaler v2 instance manager: a reconciling per-instance state
machine between desired counts, the cloud provider, and the Ray cluster
(reference: python/ray/autoscaler/v2/instance_manager/instance_manager.py:29
and instance_storage — instances move QUEUED -> REQUESTED -> ALLOCATED ->
RAY_RUNNING -> TERMINATING -> TERMINATED with an auditable status
history; the reconciler converges the fleet instead of firing one-shot
launch/terminate calls).

The Autoscaler (autoscaler.py) answers "how many of each type" from
resource demand; this layer answers "which concrete cloud instances, in
what state, and what API call moves each one forward"."""

from __future__ import annotations

import dataclasses
import logging
import time
import uuid
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


class Status:
    QUEUED = "QUEUED"                       # wanted, no cloud call yet
    REQUESTED = "REQUESTED"                 # create_node issued
    ALLOCATED = "ALLOCATED"                 # cloud reports it exists
    RAY_RUNNING = "RAY_RUNNING"             # node registered with GCS
    TERMINATING = "TERMINATING"             # delete issued
    TERMINATED = "TERMINATED"               # gone (terminal)
    ALLOCATION_FAILED = "ALLOCATION_FAILED"  # cloud lost/denied (terminal)

    TERMINAL = (TERMINATED, ALLOCATION_FAILED)


@dataclasses.dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = Status.QUEUED
    provider_id: Optional[str] = None       # cloud resource name
    ray_node_id: Optional[str] = None       # GCS node id once registered
    created_at: float = dataclasses.field(default_factory=time.monotonic)
    updated_at: float = dataclasses.field(default_factory=time.monotonic)
    history: List = dataclasses.field(default_factory=list)

    def transition(self, status: str, reason: str = ""):
        self.history.append((self.status, status, reason, time.time()))
        self.status = status
        self.updated_at = time.monotonic()


class InstanceManager:
    """Reconciles {node_type: target_count} against a NodeProvider and
    the cluster's registered nodes."""

    def __init__(self, provider, node_types: Dict[str, Dict],
                 request_timeout_s: float = 900.0):
        """node_types: name -> {"resources": {...}, "labels": {...}};
        request_timeout_s: a REQUESTED instance the cloud never lists
        within this window fails (the async create was accepted but its
        operation died — e.g. zone exhaustion — and nothing else would
        ever retry the deficit)."""
        self.provider = provider
        self.node_types = node_types
        self.request_timeout_s = request_timeout_s
        self.targets: Dict[str, int] = {}
        self.instances: Dict[str, Instance] = {}

    def set_target(self, node_type: str, count: int):
        if node_type not in self.node_types:
            raise ValueError(f"unknown node type {node_type!r}")
        self.targets[node_type] = max(0, int(count))

    # ------------------------------------------------------------- helpers
    def _live(self, node_type: Optional[str] = None) -> List[Instance]:
        return [i for i in self.instances.values()
                if i.status not in Status.TERMINAL
                and (node_type is None or i.node_type == node_type)]

    def _match_ray_nodes(self, ray_nodes: List[Dict]):
        """provider_id -> registered cluster node. Three channels:
        direct node-id match (fake/local providers), the
        ray-tpu-provider-id node label (VM providers — stamped by the
        startup script's `cli start --labels`), or a
        tpu-slice:{provider_id} resource (slice hosts)."""
        by_pid: Dict[str, Dict] = {}
        for n in ray_nodes:
            if not n.get("alive"):
                continue
            by_pid[n["node_id"]] = n
            pid_label = (n.get("labels") or {}).get("ray-tpu-provider-id")
            if pid_label:
                by_pid[pid_label] = n
            for res in n.get("total", {}):
                if res.startswith("tpu-slice:"):
                    by_pid[res[len("tpu-slice:"):]] = n
        return by_pid

    # ----------------------------------------------------------- reconcile
    def reconcile(self, ray_nodes: Optional[List[Dict]] = None) -> Dict:
        """One convergence step. Returns {launched, terminated, failed}."""
        ray_nodes = ray_nodes or []
        actions = {"launched": [], "terminated": [], "failed": []}
        try:
            cloud = set(self.provider.non_terminated_nodes())
        except Exception:
            logger.exception("provider listing failed; skipping step")
            return actions
        ray_by_pid = self._match_ray_nodes(ray_nodes)

        # 1. observe: move instances forward/mark failures from the two
        # sources of truth (cloud listing, GCS node table)
        now = time.monotonic()
        for inst in list(self.instances.values()):
            if inst.status == Status.REQUESTED:
                if inst.provider_id in cloud:
                    inst.transition(Status.ALLOCATED, "cloud lists it")
                elif now - inst.updated_at > self.request_timeout_s:
                    inst.transition(Status.ALLOCATION_FAILED,
                                    "request never materialized")
                    actions["failed"].append(inst.instance_id)
                    continue
            if inst.status in (Status.REQUESTED, Status.ALLOCATED):
                node = ray_by_pid.get(inst.provider_id)
                if node is not None:
                    inst.ray_node_id = node["node_id"]
                    inst.transition(Status.RAY_RUNNING, "node registered")
                elif inst.status == Status.ALLOCATED \
                        and inst.provider_id not in cloud:
                    inst.transition(Status.ALLOCATION_FAILED,
                                    "vanished from cloud")
                    actions["failed"].append(inst.instance_id)
            elif inst.status == Status.RAY_RUNNING \
                    and inst.provider_id not in cloud:
                inst.transition(Status.TERMINATED, "cloud terminated")
            elif inst.status == Status.TERMINATING \
                    and inst.provider_id not in cloud:
                inst.transition(Status.TERMINATED, "delete confirmed")

        # 2. converge counts per type
        for ntype, want in self.targets.items():
            live = self._live(ntype)
            # deficit: queue + request new instances
            for _ in range(want - len(live)):
                inst = Instance(instance_id=uuid.uuid4().hex[:12],
                                node_type=ntype)
                self.instances[inst.instance_id] = inst
            for inst in self._live(ntype):
                if inst.status == Status.QUEUED:
                    cfg = self.node_types[ntype]
                    try:
                        inst.provider_id = self.provider.create_node(
                            ntype, dict(cfg.get("resources") or {}),
                            dict(cfg.get("labels") or {}))
                    except Exception as e:
                        inst.transition(Status.ALLOCATION_FAILED,
                                        f"create failed: {e}")
                        actions["failed"].append(inst.instance_id)
                        continue
                    inst.transition(Status.REQUESTED, "create_node sent")
                    actions["launched"].append(inst.instance_id)
            # surplus: terminate — prefer instances that never joined the
            # cluster (cheapest to lose), then newest RAY_RUNNING
            live = self._live(ntype)
            surplus = len(live) - want
            if surplus > 0:
                def _rank(i: Instance):
                    order = {Status.QUEUED: 0, Status.REQUESTED: 1,
                             Status.ALLOCATED: 2, Status.RAY_RUNNING: 3,
                             Status.TERMINATING: 4}
                    return (order.get(i.status, 5), -i.created_at)
                for inst in sorted(live, key=_rank)[:surplus]:
                    if inst.status == Status.QUEUED:
                        inst.transition(Status.TERMINATED, "never requested")
                        continue
                    if inst.status == Status.TERMINATING:
                        continue
                    try:
                        self.provider.terminate_node(inst.provider_id)
                    except Exception:
                        logger.exception("terminate %s failed; retrying "
                                         "next step", inst.provider_id)
                        continue
                    inst.transition(Status.TERMINATING, "scale down")
                    actions["terminated"].append(inst.instance_id)
        return actions

    def summary(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for inst in self.instances.values():
            out.setdefault(inst.node_type, {})
            out[inst.node_type][inst.status] = \
                out[inst.node_type].get(inst.status, 0) + 1
        return out
