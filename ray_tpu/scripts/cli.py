"""CLI: `python -m ray_tpu.scripts.cli <cmd>` (reference:
python/ray/scripts/scripts.py — ray start/stop/status/submit/list)."""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

HEAD_FILE = "/tmp/raytpu/latest_head.json"


def _save_head(info):
    os.makedirs(os.path.dirname(HEAD_FILE), exist_ok=True)
    with open(HEAD_FILE, "w") as f:
        json.dump(info, f)


def _load_address(args) -> str:
    addr = getattr(args, "address", None) or os.environ.get("RAY_TPU_ADDRESS")
    if addr:
        return addr
    try:
        with open(HEAD_FILE) as f:
            return json.load(f)["gcs_address"]
    except OSError:
        print("no running cluster found (ray_tpu start --head first)",
              file=sys.stderr)
        sys.exit(1)


def cmd_start(args):
    from ray_tpu._private import node as node_mod
    if args.head:
        head = node_mod.start_head(
            num_cpus=args.num_cpus,
            resources=json.loads(args.resources),
            object_store_memory=args.object_store_memory or None,
            detached=True)
        _save_head({"gcs_address": head.gcs_address,
                    "node_id": head.node_id,
                    "session": head.session_name})
        print(f"head started; GCS at {head.gcs_address}")
        print(f"connect with: ray_tpu.init(address={head.gcs_address!r})")
        if args.dashboard:
            import ray_tpu
            from ray_tpu.dashboard import start_dashboard
            ray_tpu.init(address=head.gcs_address)
            start_dashboard(args.dashboard_port)
            print(f"dashboard at http://127.0.0.1:{args.dashboard_port}")
    else:
        addr = _load_address(args)
        node = node_mod.start_node(
            addr, num_cpus=args.num_cpus,
            resources=json.loads(args.resources),
            labels=json.loads(args.labels),
            object_store_memory=args.object_store_memory or None,
            detached=True)
        print(f"node {node.node_id[:12]} joined {addr}")


def cmd_stop(args):
    import signal
    import subprocess
    out = subprocess.run(["ps", "-eo", "pid,args"], capture_output=True,
                         text=True).stdout
    killed = 0
    for line in out.splitlines():
        parts = line.split(None, 1)
        if len(parts) != 2:
            continue
        pid, cmd = parts
        if ("ray_tpu._private.gcs" in cmd
                or "ray_tpu._private.node_manager" in cmd
                or "ray_tpu._private.worker_main" in cmd):
            try:
                os.kill(int(pid), signal.SIGTERM)
                killed += 1
            except OSError:
                pass
    print(f"stopped {killed} processes")
    try:
        os.unlink(HEAD_FILE)
    except OSError:
        pass


def _status_summary(ray_tpu, state):
    summary = state.cluster_summary()
    # autoscaler view: aggregate queued lease demand per resource shape
    # (reference: `ray status` resource demand section)
    demand = {}
    for n in ray_tpu.nodes():
        for d in n.get("pending_demand") or []:
            key = json.dumps(d, sort_keys=True)
            demand[key] = demand.get(key, 0) + 1
    summary["pending_demand"] = [
        {"shape": json.loads(k), "count": v} for k, v in demand.items()]
    return summary


def _fmt_metric(v):
    if v is None:
        return "-"
    if abs(v) >= 1e6:
        return f"{v:,.0f}"
    if abs(v) >= 100:
        return f"{v:.1f}"
    return f"{v:.3f}"


def _metrics_table(state, window: float, max_rows: int = 40) -> str:
    """One line per live metric: counters show rate, gauges latest+avg,
    histograms p50/p95 + observation rate — all windowed over the GCS
    time-series plane."""
    lines = [f"{'METRIC':<40} {'KIND':<10} {'WINDOW':>7}  VALUES"]
    for row in state.list_metric_series()[:max_rows]:
        name, kind = row["name"], row["kind"]
        try:
            if kind == "counter":
                rate = state.query_metrics(name, window, "rate")["value"]
                vals = f"rate/s={_fmt_metric(rate)}"
            elif kind == "histogram":
                p50 = state.query_metrics(name, window, "p50")["value"]
                p95 = state.query_metrics(name, window, "p95")["value"]
                rate = state.query_metrics(name, window, "rate")["value"]
                vals = (f"p50={_fmt_metric(p50)} p95={_fmt_metric(p95)} "
                        f"obs/s={_fmt_metric(rate)}")
            else:
                cur = state.query_metrics(name, window, "latest")["value"]
                avg = state.query_metrics(name, window, "avg")["value"]
                vals = f"latest={_fmt_metric(cur)} avg={_fmt_metric(avg)}"
        except Exception as e:
            vals = f"<query failed: {e}>"
        lines.append(f"{name:<40} {kind:<10} {window:>6.0f}s  {vals}")
    if len(lines) == 1:
        lines.append("  (no metrics pushed yet)")
    return "\n".join(lines)


# (metric, agg, label) rows of the --watch memory pane: arena occupancy
# + span/stripe stats + leak gauge + the PR 5/11 data-plane counters
# (previously these reached only /metrics and get_node_info)
_MEMORY_PANE_ROWS = [
    ("store_bytes_in_use", "latest", "arena bytes in use"),
    ("store_capacity_bytes", "latest", "arena capacity"),
    ("store_objects", "latest", "live objects"),
    ("store_live_spans", "latest", "spanning objects"),
    ("store_span_bytes", "latest", "bytes in spans"),
    ("store_stripes_claimed", "latest", "stripes claimed by spans"),
    ("store_stripe_max_utilization", "latest", "fullest stripe fraction"),
    ("store_largest_hole_bytes", "latest", "largest free hole"),
    ("store_leaked_bytes", "latest", "leaked bytes (ledger sweep)"),
    ("store_leaked_objects", "latest", "leaked objects"),
    ("data_plane_bytes_in_total", "rate", "data-plane B/s in"),
    ("data_plane_bytes_out_total", "rate", "data-plane B/s out"),
    ("data_plane_chunks_in_total", "rate", "data-plane chunks/s in"),
    ("data_plane_chunks_out_total", "rate", "data-plane chunks/s out"),
    ("data_plane_active_conns", "latest", "data-plane connections"),
    ("data_plane_receiving", "latest", "receives in progress"),
]


def _memory_pane(state, window: float) -> str:
    """Memory/data-plane pane for `status --watch`: windowed values of
    the store + transfer gauges over the GCS time-series plane."""
    lines = [f"{'MEMORY / DATA PLANE':<40} {'AGG':<7} {'VALUE':>14}"]
    shown = 0
    for name, agg, label in _MEMORY_PANE_ROWS:
        try:
            v = state.query_metrics(name, window, agg)["value"]
        except Exception:
            v = None
        if v is None:
            continue
        shown += 1
        lines.append(f"{label:<40} {agg:<7} {_fmt_metric(v):>14}")
    if not shown:
        lines.append("  (no store metrics pushed yet)")
    return "\n".join(lines)


def _control_pane(state) -> str:
    """Control-plane pane for `status --watch`: GCS RPC p99 by the
    top-3 handlers, in-flight launches with their current phase, pubsub
    backlog, and black boxes on disk — straight from the GCS's live
    handler stats (control_plane_stats), not the windowed TS plane."""
    try:
        stats = state.control_plane_stats(top_n=3)
    except Exception as e:
        return f"CONTROL PLANE\n  (unavailable: {e})"
    lines = [f"{'CONTROL PLANE':<40} "
             f"rpc in-flight={stats.get('rpc_inflight', 0)}  "
             f"pubsub backlog={stats.get('pubsub', {}).get('backlog', 0)}  "
             f"black boxes={stats.get('blackboxes', 0)}"]
    for h in stats.get("handlers") or []:
        lines.append(
            f"  rpc {h['handler']:<28} p50={_fmt_metric(h['p50_ms'])}ms "
            f"p99={_fmt_metric(h['p99_ms'])}ms calls={h['calls']} "
            f"slow={h['slow']} err={h['errors']}")
    launches = stats.get("launches") or []
    for ln in launches[:5]:
        lines.append(
            f"  launch {ln.get('actor', '?'):<24} phase={ln['phase']} "
            f"({_fmt_metric(ln['phase_age_s'])}s) "
            f"total={_fmt_metric(ln['age_s'])}s retries={ln['retries']}")
    if not launches:
        recent = stats.get("recent_launch_ms") or []
        if recent:
            lines.append(
                f"  (no launches in flight; last "
                f"{len(recent)} took {_fmt_metric(min(recent))}-"
                f"{_fmt_metric(max(recent))}ms)")
        else:
            lines.append("  (no launches in flight)")
    return "\n".join(lines)


def cmd_status(args):
    import ray_tpu
    from ray_tpu.util import state
    ray_tpu.init(address=_load_address(args))
    if not getattr(args, "watch", False):
        print(json.dumps(_status_summary(ray_tpu, state), indent=2,
                         default=str))
        return
    # --watch: live terminal view over the time-series plane (reference:
    # `ray status` is point-in-time; the TS plane makes a refresh loop
    # show windowed rates/percentiles instead of instants)
    interval = max(0.5, float(getattr(args, "interval", 2.0)))
    window = float(getattr(args, "window", 30.0))
    try:
        while True:
            summary = _status_summary(ray_tpu, state)
            table = _metrics_table(state, window)
            sys.stdout.write("\x1b[2J\x1b[H")   # clear + home
            print(f"ray_tpu status --watch  (refresh {interval:.1f}s, "
                  f"window {window:.0f}s, ctrl-c to exit)\n")
            print(json.dumps(summary, default=str))
            print()
            print(_control_pane(state))
            print()
            print(_memory_pane(state, window))
            print()
            print(table)
            sys.stdout.flush()
            time.sleep(interval)
    except KeyboardInterrupt:
        pass


def cmd_up(args):
    from ray_tpu.autoscaler import launcher
    # --block keeps this CLI alive as the cluster's supervisor; without
    # it the cluster must outlive the CLI (no PDEATHSIG)
    handle = launcher.up(args.config, detached=not args.block)
    print(f"cluster {handle.config['cluster_name']} up; "
          f"GCS at {handle.gcs_address}")
    print(f"connect with: ray_tpu.init(address={handle.gcs_address!r})")
    if args.block:
        import signal
        stop = False

        def _sig(*_):
            nonlocal stop
            stop = True
        signal.signal(signal.SIGINT, _sig)
        signal.signal(signal.SIGTERM, _sig)
        while not stop:
            time.sleep(1)
        handle.down()
        print("cluster down")


def cmd_down(args):
    from ray_tpu.autoscaler import launcher
    if launcher.down_from_state():
        print("cloud nodes terminated")
    cmd_stop(args)


def cmd_list(args):
    import ray_tpu
    from ray_tpu.util import state
    ray_tpu.init(address=_load_address(args))
    fn = {"nodes": state.list_nodes, "actors": state.list_actors,
          "tasks": state.list_tasks, "jobs": state.list_jobs,
          "placement-groups": state.list_placement_groups}[args.kind]
    print(json.dumps(fn(), indent=2, default=str))


def cmd_timeline(args):
    """Export the task timeline as a chrome://tracing JSON (reference:
    `ray timeline`)."""
    import ray_tpu
    ray_tpu.init(address=_load_address(args))
    out = args.output or "ray-tpu-timeline.json"
    ray_tpu.timeline(out)
    print(f"wrote {out} (open in chrome://tracing or Perfetto)")


def cmd_blackbox(args):
    """Stitch surviving crash black boxes into one cross-node
    post-mortem timeline. Needs no live cluster — it reads the NDJSON
    boxes off disk, which is the point: the GCS/node that would answer
    RPCs is exactly what died."""
    from ray_tpu._private import blackbox as bb
    paths = []
    for p in args.paths or []:
        if os.path.isdir(p):
            paths.extend(bb.scan_boxes(p))
        else:
            paths.append(p)
    if not args.paths:
        import glob
        for d in sorted(glob.glob("/tmp/raytpu/*/blackbox")):
            paths.extend(bb.scan_boxes(d))
    if not paths:
        print("no black boxes found (pass a session blackbox dir or "
              "box files)", file=sys.stderr)
        sys.exit(1)
    merged = bb.stitch(paths, max_skew_s=args.max_skew)
    if args.json:
        print(json.dumps(merged, indent=2, default=str))
        return
    print(f"{len(merged['boxes'])} black boxes:")
    for b in merged["boxes"]:
        print(f"  {b['process']:<24} node={b['node_id'][:12] or '-':<12} "
              f"records={b['records']:<6} "
              f"offset={b['clock_offset_s']:+.3f}s  seal={b['seal_reason']}")
    print()
    rows = merged["records"]
    shown = rows[-args.limit:] if args.limit and len(rows) > args.limit \
        else rows
    if len(shown) < len(rows):
        print(f"(showing last {len(shown)} of {len(rows)} records)")
    for m in shown:
        rec = m["rec"]
        kind = rec.get("kind", "?")
        t = time.strftime("%H:%M:%S",
                          time.localtime(m["adj_ts"])) + \
            f".{int((m['adj_ts'] % 1) * 1000):03d}"
        if kind == "event":
            dur = ""
            if rec.get("start") and rec.get("end"):
                dur = f" {1e3 * (rec['end'] - rec['start']):.1f}ms"
            detail = f"{rec.get('name')}{dur}"
            attrs = rec.get("attrs") or {}
            if attrs:
                detail += " " + json.dumps(attrs, default=str)[:80]
        elif kind == "metrics":
            detail = f"snapshot ({len(rec.get('metrics') or [])} metrics)"
        elif kind == "seal":
            detail = f"SEALED: {rec.get('reason')}"
        elif kind == "marker":
            detail = " ".join(f"{k}={v}" for k, v in rec.items()
                              if k not in ("kind", "ts", "seq"))
        elif kind == "header":
            detail = (f"pid={rec.get('pid')}"
                      + (" (rotated)" if rec.get("rotated") else ""))
        else:
            detail = json.dumps(rec, default=str)[:100]
        print(f"{t} {m['process']:<24} {kind:<8} {detail}")


def _fmt_bytes(n) -> str:
    n = n or 0
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TB"


def _memory_sorted(rows, sort: str):
    """Deterministic ordering for the memory table. sort: size (desc),
    age (desc — oldest first is what a leak hunt wants), node."""
    if sort == "age":
        return sorted(rows, key=lambda r: -(r.get("age_s") or 0.0))
    if sort == "node":
        return sorted(rows, key=lambda r: (str(r.get("node_id") or "~"),
                                           -(r.get("size_bytes") or 0)))
    return sorted(rows, key=lambda r: -(r.get("size_bytes") or 0))


def _memory_grouped(rows, by: str):
    """Aggregate object rows by owner | node | kind: object count,
    total bytes, pinned count, leaked bytes per group."""
    groups = {}
    for r in rows:
        if by == "node":
            key = str(r.get("node_id") or "-")
        elif by == "owner":
            key = str(r.get("owner") or "-")
        else:
            key = str(r.get("kind") or "-")
        g = groups.setdefault(key, {"group": key, "objects": 0,
                                    "bytes": 0, "pinned": 0,
                                    "leaked_bytes": 0})
        g["objects"] += 1
        g["bytes"] += r.get("size_bytes") or 0
        if r.get("pins"):
            g["pinned"] += 1
        if r.get("leaked"):
            g["leaked_bytes"] += r.get("size_bytes") or 0
    return sorted(groups.values(), key=lambda g: -g["bytes"])


def _format_memory_rows(rows) -> str:
    lines = [f"{'OBJECT ID':<34} {'KIND':<10} {'SIZE':>10} {'PINS':>5} "
             f"{'AGE':>8} {'SPAN':>5} {'LEAK':>5}  OWNER / NODES"]
    for r in rows:
        age = r.get("age_s")
        owner = r.get("owner") or r.get("location") or "-"
        nodes = ",".join(n[:8] for n in r.get("locations") or ())
        if not nodes and r.get("node_id"):
            nodes = str(r["node_id"])[:8]
        lines.append(
            f"{r.get('object_id', '?'):<34} {r.get('kind', '?'):<10} "
            f"{_fmt_bytes(r.get('size_bytes')):>10} "
            f"{r.get('pins') if r.get('pins') is not None else '-':>5} "
            f"{f'{age:.0f}s' if age is not None else '-':>8} "
            f"{'yes' if r.get('is_span') else '-':>5} "
            f"{'LEAK' if r.get('leaked') else '-':>5}  "
            f"{str(owner)[:24]} @{nodes or '-'}")
    return "\n".join(lines)


def cmd_memory(args):
    """Cluster memory observability (reference: `ray memory` + the state
    observability object table): every live object with owner, size,
    placement (stripe/span), pin count, and age — local arena truth
    joined with GCS object-ledger provenance. `--leaked` shows only
    objects flagged by the leak detector; `--group-by owner|node|kind`
    aggregates; `--nodes` appends per-node occupancy/fragmentation."""
    import ray_tpu
    from ray_tpu.util import state
    ray_tpu.init(address=_load_address(args))
    rows = state.list_objects(limit=args.limit)
    if args.leaked:
        rows = [r for r in rows if r.get("leaked")]
    total = sum(r.get("size_bytes") or 0 for r in rows)
    leaked = sum(r.get("size_bytes") or 0 for r in rows if r.get("leaked"))
    if args.group_by:
        groups = _memory_grouped(rows, args.group_by)
        print(f"{'GROUP':<40} {'OBJECTS':>8} {'BYTES':>12} "
              f"{'PINNED':>7} {'LEAKED':>12}")
        for g in groups:
            print(f"{g['group'][:40]:<40} {g['objects']:>8} "
                  f"{_fmt_bytes(g['bytes']):>12} {g['pinned']:>7} "
                  f"{_fmt_bytes(g['leaked_bytes']):>12}")
    else:
        print(_format_memory_rows(_memory_sorted(rows, args.sort)))
    print(f"-- {len(rows)} objects, {_fmt_bytes(total)} total"
          + (f", {_fmt_bytes(leaked)} leaked" if leaked else ""))
    if getattr(args, "nodes", False):
        summary = state.memory_summary()
        for n in summary["nodes"]:
            st = n.get("store") or {}
            print(f"\nnode {n['node_id'][:12]}: "
                  f"{_fmt_bytes(st.get('bytes_in_use'))} / "
                  f"{_fmt_bytes(st.get('capacity'))} in use, "
                  f"{st.get('num_objects', '?')} objects, "
                  f"{st.get('num_spans', 0)} spans, "
                  f"{st.get('spilled_objects', 0)} spilled")
            for s in (st.get("fragmentation") or {}).get("stripes", []):
                print(f"  stripe {s['stripe']}: live "
                      f"{_fmt_bytes(s['live'])} / "
                      f"{_fmt_bytes(s['capacity'])}, free "
                      f"{_fmt_bytes(s['free'])}, largest hole "
                      f"{_fmt_bytes(s['largest_hole'])}, "
                      f"{s['objects']} objects")
        led = summary.get("ledger")
        if led:
            print(f"ledger: {led['entries']} rows, "
                  f"{led['leaked_objects']} leaked "
                  f"({_fmt_bytes(led['leaked_bytes'])})")


def cmd_submit(args):
    import ray_tpu
    from ray_tpu.job_submission import JobSubmissionClient
    ray_tpu.init(address=_load_address(args))
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=" ".join(args.entrypoint))
    print(f"submitted {job_id}")
    if args.wait:
        status = client.wait_until_finished(job_id, timeout=args.timeout)
        print(client.get_job_logs(job_id))
        print(f"job {job_id}: {status}")
        sys.exit(0 if status == "SUCCEEDED" else 1)


def cmd_stack(args):
    """Live Python stacks of every cluster process (reference:
    `ray stack` — py-spy over local PIDs; here each daemon serves its
    own frames over RPC, so it works cluster-wide without ptrace)."""
    import ray_tpu
    from ray_tpu.util.tracing import cluster_stacks, format_cluster_stacks
    ray_tpu.init(address=_load_address(args), ignore_reinit_error=True)
    text = format_cluster_stacks(cluster_stacks())
    if getattr(args, "output", None):
        with open(args.output, "w") as f:
            f.write(text + "\n")
        print(f"wrote {args.output}")
    else:
        print(text)


def cmd_export_traces(args):
    """Export spans as OTLP JSON (file and/or OTLP/HTTP collector)."""
    import ray_tpu
    from ray_tpu.util.tracing import export_otlp
    ray_tpu.init(address=_load_address(args), ignore_reinit_error=True)
    payload = export_otlp(filename=args.output, endpoint=args.endpoint)
    n = sum(len(ss["spans"]) for rs in payload["resourceSpans"]
            for ss in rs["scopeSpans"])
    where = args.output or args.endpoint or "stdout"
    if not args.output and not args.endpoint:
        print(json.dumps(payload))
    print(f"exported {n} spans to {where}", file=sys.stderr)


def cmd_serve_deploy(args):
    """Deploy applications from a declarative YAML config (reference:
    python/ray/serve/scripts.py `serve deploy`)."""
    import ray_tpu
    from ray_tpu.serve.schema import deploy_from_config
    ray_tpu.init(address=_load_address(args), ignore_reinit_error=True)
    handles = deploy_from_config(args.config)
    print(f"deployed {len(handles)} application(s)")
    from ray_tpu import serve
    print(json.dumps(serve.status(), indent=2))


def cmd_serve_status(args):
    import ray_tpu
    from ray_tpu import serve
    ray_tpu.init(address=_load_address(args), ignore_reinit_error=True)
    out = {"applications": serve.status(), "proxies": serve.proxies()}
    try:
        slo = serve.slo_status()
        if any(slo.values()):
            out["slo"] = slo
    except Exception:
        pass
    print(json.dumps(out, indent=2, default=str))


def cmd_serve_fleet(args):
    """Fleet-plane view (serve/fleet.py): per-deployment scale-to-zero
    state, shell-pool occupancy, revival counts + cold-start
    percentiles, and configured tenant quotas."""
    import ray_tpu
    from ray_tpu import serve
    ray_tpu.init(address=_load_address(args), ignore_reinit_error=True)
    out = serve.fleet_status()
    try:
        quotas = serve.get_tenant_quotas()
        if quotas:
            out["tenant_quotas"] = quotas
    except Exception:
        pass
    print(json.dumps(out, indent=2, default=str))


def cmd_serve_delete(args):
    import ray_tpu
    from ray_tpu import serve
    ray_tpu.init(address=_load_address(args), ignore_reinit_error=True)
    if getattr(args, "all", False):
        serve.shutdown()
        print("serve shut down")
        return
    if not args.name:
        print("serve delete: provide an application name or --all",
              file=sys.stderr)
        sys.exit(2)
    serve.delete(args.name)
    print(f"deleted application {args.name!r}")


def cmd_serve_shutdown(args):
    import ray_tpu
    from ray_tpu import serve
    ray_tpu.init(address=_load_address(args), ignore_reinit_error=True)
    serve.shutdown()
    print("serve shut down")


def cmd_lint(args):
    """Runtime-aware static analysis (rtlint): RT001 loop-blocking,
    RT002 jit-retrace, RT003 cross-thread mutation, RT004 swallowed
    exceptions in daemons, RT005 msgpack-unsafe RPC returns. Exits
    non-zero on NEW findings (baseline + inline suppressions pass)."""
    from ray_tpu.devtools.lint.cli import run_from_args
    sys.exit(run_from_args(args))


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("start")
    ps.add_argument("--head", action="store_true")
    ps.add_argument("--address", default=None)
    ps.add_argument("--num-cpus", type=float, default=None)
    ps.add_argument("--resources", default="{}")
    ps.add_argument("--labels", default="{}",
                    help="node labels JSON (e.g. the autoscaler's "
                         "ray-tpu-provider-id)")
    ps.add_argument("--object-store-memory", type=int, default=0)
    ps.add_argument("--dashboard", action="store_true")
    ps.add_argument("--dashboard-port", type=int, default=8265)
    ps.set_defaults(fn=cmd_start)

    pstop = sub.add_parser("stop")
    pstop.set_defaults(fn=cmd_stop)

    pu = sub.add_parser("up", help="bring up a cluster from a YAML spec")
    pu.add_argument("config")
    pu.add_argument("--block", action="store_true",
                    help="stay attached; ctrl-c tears the cluster down")
    pu.set_defaults(fn=cmd_up)

    pd = sub.add_parser("down", help="tear down the launched cluster")
    pd.set_defaults(fn=cmd_down)

    pst = sub.add_parser("status")
    pst.add_argument("--address", default=None)
    pst.add_argument("--watch", "-w", action="store_true",
                     help="live view: refresh cluster summary + windowed "
                          "metrics (rates / p50 / p95) until ctrl-c")
    pst.add_argument("--interval", type=float, default=2.0,
                     help="--watch refresh cadence in seconds")
    pst.add_argument("--window", type=float, default=30.0,
                     help="--watch metric aggregation window in seconds")
    pst.set_defaults(fn=cmd_status)

    pl = sub.add_parser("list")
    pl.add_argument("kind", choices=["nodes", "actors", "tasks", "jobs",
                                     "placement-groups"])
    pl.add_argument("--address", default=None)
    pl.set_defaults(fn=cmd_list)

    pt = sub.add_parser("timeline")
    pt.add_argument("--address", default=None)
    pt.add_argument("--output", "-o", default=None)
    pt.set_defaults(fn=cmd_timeline)

    pbb = sub.add_parser(
        "blackbox", help="stitch crash black boxes into one cross-node "
        "post-mortem timeline (reads NDJSON off disk; no cluster needed)")
    pbb.add_argument("paths", nargs="*",
                     help="box files or session blackbox dirs; default "
                          "scans /tmp/raytpu/*/blackbox")
    pbb.add_argument("--json", action="store_true",
                     help="emit the merged timeline as JSON")
    pbb.add_argument("--limit", type=int, default=200,
                     help="max records to print (newest kept; 0 = all)")
    pbb.add_argument("--max-skew", type=float, default=0.0,
                     help="clamp clock offsets larger than this many "
                          "seconds to 0 (implausible-skew guard)")
    pbb.set_defaults(fn=cmd_blackbox)

    pm = sub.add_parser(
        "memory", help="cluster object/memory observability "
        "(arena truth joined with object-ledger provenance)")
    pm.add_argument("--address", default=None)
    pm.add_argument("--sort", choices=["size", "age", "node"],
                    default="size",
                    help="row ordering (size desc, age desc, node)")
    pm.add_argument("--group-by", dest="group_by",
                    choices=["owner", "node", "kind"], default=None,
                    help="aggregate instead of listing per object")
    pm.add_argument("--leaked", action="store_true",
                    help="only objects flagged by the leak detector")
    pm.add_argument("--limit", type=int, default=1000)
    pm.add_argument("--nodes", action="store_true",
                    help="append per-node occupancy + per-stripe "
                         "fragmentation (live/free/largest hole)")
    pm.set_defaults(fn=cmd_memory)

    pj = sub.add_parser("submit")
    pj.add_argument("--address", default=None)
    pj.add_argument("--wait", action="store_true")
    pj.add_argument("--timeout", type=float, default=600)
    pj.add_argument("entrypoint", nargs=argparse.REMAINDER)
    pj.set_defaults(fn=cmd_submit)

    pstack = sub.add_parser("stack",
                            help="dump live Python stacks cluster-wide")
    pstack.add_argument("--address", default=None)
    pstack.add_argument("--output", "-o", default=None,
                        help="write the dump to a file instead of stdout")
    pstack.set_defaults(fn=cmd_stack)

    ptr = sub.add_parser("export-traces",
                         help="export spans as OTLP JSON")
    ptr.add_argument("--address", default=None)
    ptr.add_argument("--output", "-o", default=None)
    ptr.add_argument("--endpoint", default=None,
                     help="OTLP/HTTP collector base URL")
    ptr.set_defaults(fn=cmd_export_traces)

    psrv = sub.add_parser("serve", help="serve control plane")
    srv_sub = psrv.add_subparsers(dest="serve_cmd", required=True)
    sd = srv_sub.add_parser("deploy",
                            help="deploy apps from a YAML config")
    sd.add_argument("config")
    sd.add_argument("--address", default=None)
    sd.set_defaults(fn=cmd_serve_deploy)
    ss = srv_sub.add_parser("status")
    ss.add_argument("--address", default=None)
    ss.set_defaults(fn=cmd_serve_status)
    sf = srv_sub.add_parser(
        "fleet", help="fleet plane: scale-to-zero state, shell pool, "
                      "cold-start percentiles, tenant quotas")
    sf.add_argument("--address", default=None)
    sf.set_defaults(fn=cmd_serve_fleet)
    sdel = srv_sub.add_parser("delete")
    sdel.add_argument("name", nargs="?", default=None)
    sdel.add_argument("--all", action="store_true",
                      help="delete every application (serve shutdown)")
    sdel.add_argument("--address", default=None)
    sdel.set_defaults(fn=cmd_serve_delete)
    ssh = srv_sub.add_parser("shutdown")
    ssh.add_argument("--address", default=None)
    ssh.set_defaults(fn=cmd_serve_shutdown)

    plint = sub.add_parser(
        "lint", help="runtime-aware static analysis (rtlint RT001..RT005)")
    from ray_tpu.devtools.lint.cli import add_lint_args
    add_lint_args(plint)
    plint.set_defaults(fn=cmd_lint)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
