"""@ray_tpu.remote for classes: ActorClass / ActorHandle (reference:
python/ray/actor.py:293 ActorClass._remote, :721 method wrappers).

An ActorHandle is picklable and carries (actor_id, method names, owner gcs),
so handles can be passed into tasks and other actors; calls from any holder
go directly to the actor's worker over its own connection (reference:
direct worker→worker transport, actor_task_submitter.h:75)."""

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional

from ray_tpu.remote_function import (_resources_from_options,
                                     _scheduling_from_options)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._name, args, kwargs,
                                    self._handle._options)

    def bind(self, *args, **kwargs):
        """Capture a compiled-DAG node (reference: dag class_node bind)."""
        from ray_tpu.dag.nodes import ClassMethodNode
        return ClassMethodNode(self._handle, self._name, args, kwargs)

    def options(self, **opts):
        # a plain instance, NOT a class defined in this closure: the
        # closure-class pattern forms a reference cycle (class -> method
        # -> cell -> handle) that defers the owner handle's
        # refcount-driven __del__ (= actor termination) to a gc pass
        return _BoundActorMethod(self._handle, self._name, opts)


class _BoundActorMethod:
    __slots__ = ("_handle", "_name", "_opts")

    def __init__(self, handle, name, opts):
        self._handle = handle
        self._name = name
        self._opts = opts

    def remote(self, *args, **kwargs):
        merged = {**self._handle._options, **self._opts}
        return self._handle._invoke(self._name, args, kwargs, merged)


class ActorHandle:
    def __init__(self, actor_id: str, method_names: List[str],
                 options: Optional[Dict[str, Any]] = None,
                 is_owner: bool = False):
        self._actor_id = actor_id
        self._method_names = list(method_names)
        self._options = options or {}
        # The original handle returned by ActorClass.remote() owns the actor's
        # lifetime: dropping it terminates a non-detached actor (reference:
        # actor GC on out-of-scope handles, gcs_actor_manager.cc ownership).
        self._is_owner = is_owner

    @property
    def _id(self) -> str:
        return self._actor_id

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._method_names:
            raise AttributeError(
                f"actor has no method {name!r}; methods: {self._method_names}")
        # NOT cached on the handle: that would create a reference cycle
        # (handle -> method -> handle) deferring the owner handle's
        # refcount-driven __del__ (= actor termination) to a gc pass
        return ActorMethod(self, name)

    def _invoke(self, method: str, args, kwargs, opts: Dict[str, Any]):
        from ray_tpu import _get_worker
        w = _get_worker()
        num_returns = opts.get("num_returns") \
            or opts.get("method_num_returns", {}).get(method, 1)
        if num_returns == "streaming":
            return w.submit_actor_streaming(
                self._actor_id, method, args, kwargs,
                concurrency_group=opts.get("concurrency_group"),
                backpressure=opts.get("_generator_backpressure"))
        refs = w.submit_actor_task(
            self._actor_id, method, args, kwargs,
            num_returns=num_returns,
            max_task_retries=opts.get("max_task_retries", 0),
            concurrency_group=opts.get("concurrency_group"))
        return refs[0] if num_returns == 1 else refs

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_names,
                              self._options))

    def __del__(self):
        if not getattr(self, "_is_owner", False):
            return
        try:
            import ray_tpu
            if ray_tpu.is_initialized():
                # fire-and-forget: __del__ can run via GC on ANY thread —
                # including the worker's own event-loop thread (e.g. during
                # cloudpickle of a task argument) — so a blocking bridge
                # here deadlocks the loop on itself
                w = ray_tpu._get_worker()
                if w.core._shutdown:
                    # too late to reach the GCS; finish_job reaps the
                    # job's actors server-side (spawning here would leak
                    # a task through the drained shutdown)
                    return
                import asyncio

                def _kick():
                    if not w.core._shutdown:
                        w.core._spawn(w.core.kill_actor_async(
                            self._actor_id, no_restart=True))

                w.core.loop.call_soon_threadsafe(_kick)
        except Exception:
            pass

    def __repr__(self):
        return f"ActorHandle({self._actor_id[:12]})"


def _public_methods(cls) -> List[str]:
    names = []
    for name, member in inspect.getmembers(cls):
        if name.startswith("__") and name != "__call__":
            continue
        if callable(member):
            names.append(name)
    return names


def _method_groups(cls) -> Dict[str, str]:
    """method name -> concurrency group declared via @ray_tpu.method."""
    inner = getattr(cls, "__ray_tpu_actual_class__", cls)
    out = {}
    for name, member in inspect.getmembers(inner):
        group = getattr(member, "__concurrency_group__", None)
        if group:
            out[name] = group
    return out


def _method_num_returns(cls) -> Dict[str, int]:
    """method name -> num_returns declared via @ray_tpu.method."""
    inner = getattr(cls, "__ray_tpu_actual_class__", cls)
    out = {}
    for name, member in inspect.getmembers(inner):
        n = getattr(member, "__num_returns__", None)
        if n is not None:
            out[name] = int(n)
    return out


def method(*, concurrency_group: Optional[str] = None, num_returns=None):
    """Method decorator (reference: ray.method — python/ray/actor.py).
    Declares the concurrency group an actor method executes in; groups
    and their widths are given at class level via
    ``@ray_tpu.remote(concurrency_groups={"io": 2})``."""
    def deco(fn):
        if concurrency_group:
            fn.__concurrency_group__ = concurrency_group
        if num_returns is not None:
            fn.__num_returns__ = num_returns
        return fn
    return deco


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = options or {}
        self.__name__ = getattr(cls, "__name__", "ActorClass")

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu.client import current_client
        cc = current_client()
        if cc is not None:   # client-mode hook (reference: client_mode_hook)
            return cc.remote(self._cls, **self._options).remote(
                *args, **kwargs)
        from ray_tpu import _get_worker
        w = _get_worker()
        opts = self._options
        actor_id = w.create_actor(
            self._cls, args, kwargs,
            resources=_resources_from_options(opts),
            name=opts.get("name"),
            namespace=opts.get("namespace"),
            max_restarts=opts.get("max_restarts", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            scheduling=_scheduling_from_options(opts),
            lifetime=opts.get("lifetime"),
            method_names=_public_methods(self._cls),
            runtime_env=opts.get("runtime_env"),
            concurrency_groups=opts.get("concurrency_groups"),
            method_groups=_method_groups(self._cls))
        return ActorHandle(
            actor_id, _public_methods(self._cls),
            {"max_task_retries": opts.get("max_task_retries", 0),
             "method_num_returns": _method_num_returns(self._cls)},
            is_owner=opts.get("lifetime") != "detached")

    def options(self, **new_options) -> "ActorClass":
        return ActorClass(self._cls, {**self._options, **new_options})

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self.__name__}' cannot be instantiated directly; "
            "use .remote().")
