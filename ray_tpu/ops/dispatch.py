"""Attention dispatch: pick the right kernel for the current mesh.

Under a multi-device mesh the attention runs as a shard_map island inside
the jitted step — Pallas kernels and ring collectives both need per-shard
(local) views, which GSPMD alone can't give them. On one device it's the
Pallas flash kernel (TPU) or the XLA reference (CPU tests).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP, AXIS_SEQ, AXIS_TENSOR


def _on_tpu() -> bool:
    """True on real TPU hardware, including device plugins whose platform
    string isn't literally "tpu" (the device kind names the generation)."""
    if jax.default_backend() == "tpu":
        return True
    try:
        d = jax.devices()[0]
    except Exception:
        return False
    return "tpu" in (getattr(d, "device_kind", "") or "").lower() \
        or "tpu" in (d.platform or "").lower()


def attention(q, k, v, causal: bool = True, impl: str = "auto"):
    """q[B,L,H,D], k/v[B,L,Hkv,D] — global (logical) shapes."""
    mesh = mesh_lib.current_mesh()
    multi = mesh is not None and mesh.size > 1
    seq_sharded = multi and mesh.shape[AXIS_SEQ] > 1
    if impl == "auto":
        if seq_sharded:
            impl = "ring"
        elif multi:
            impl = "sharded_local"   # per-shard flash/ref under shard_map
        elif _on_tpu():
            impl = "flash"
        else:
            impl = "reference"
    if impl in ("ring", "sharded_local"):
        if mesh is None:
            raise ValueError("sharded attention needs a mesh (use_mesh(...))")
        B, L, H, D = q.shape
        Hkv = k.shape[2]
        t = mesh.shape[AXIS_TENSOR]
        s = mesh.shape[AXIS_SEQ]
        bsz = mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
        if impl == "ring" and L % s != 0:
            return mha_reference(q, k, v, causal=causal)
        batch_ax = (AXIS_DATA, AXIS_FSDP) if B % bsz == 0 else None
        # heads shard over tensor only when q AND kv head counts divide it
        # (keeps the GQA repeat factor consistent per shard)
        head_ax = AXIS_TENSOR if (H % t == 0 and Hkv % t == 0) else None
        if impl == "ring":
            spec = P(batch_ax, AXIS_SEQ, head_ax, None)
            body = functools.partial(ring_attention, axis_name=AXIS_SEQ,
                                     causal=causal)
        else:
            # seq axis unsharded: each (batch, head) shard holds the full
            # sequence — run the flash kernel (or XLA ref on CPU) locally;
            # pallas can't be auto-partitioned by GSPMD, hence shard_map
            spec = P(batch_ax, None, head_ax, None)
            body = functools.partial(flash_attention, causal=causal)
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_rep=False)
        return fn(q, k, v)
    if impl == "flash":
        return flash_attention(q, k, v, causal=causal)
    return mha_reference(q, k, v, causal=causal)
