"""Ring attention: exact causal attention over a sequence-sharded axis.

Context parallelism for long sequences (SURVEY.md §5.7 — absent from the
reference, which leaves intra-model parallelism to the training framework).
Each device holds a contiguous sequence shard of Q/K/V; K/V blocks rotate
around the `seq` mesh axis via ppermute while every device accumulates
attention of its local queries against each passing block with an online
(streaming) softmax — compute overlaps the ICI transfer, memory stays
O(L_local), and the result is bit-for-bit exact attention (blockwise /
RingAttention construction).

Use inside shard_map with q,k,v already sharded on the seq axis:

    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh, in_specs=P(None, "seq", None, None), out_specs=...)(q, k, v)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None):
    """q[B,Lq,H,D], k/v[B,Lk,Hkv,D] — local shards; returns local [B,Lq,H,D]."""
    B, Lq, H, D = q.shape
    _, Lk, Hkv, _ = k.shape
    scale = scale if scale is not None else D ** -0.5
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)

    q32 = q.astype(jnp.float32)

    def step(carry, i):
        acc, m, l, kb, vb = carry
        # the block currently held originated on device (my_idx - i) % size
        src = (my_idx - i) % axis_size
        s = jnp.einsum("bqhd,bkhd->bhqk", q32,
                       kb.astype(jnp.float32)) * scale
        if causal:
            qpos = my_idx * Lq + jnp.arange(Lq)
            kpos = src * Lk + jnp.arange(Lk)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vb.astype(jnp.float32))
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + pv
        # rotate K/V around the ring for the next step
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (acc, m_new, l_new, kb, vb), None

    acc0 = jnp.zeros((B, Lq, H, D), jnp.float32)
    m0 = jnp.full((B, H, Lq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Lq), jnp.float32)
    (acc, m, l, _, _), _ = lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(axis_size))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(q.dtype)
