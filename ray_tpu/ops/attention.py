"""Attention kernels.

`flash_attention` is a Pallas TPU kernel pair (tiled online-softmax forward
+ FlashAttention-2-style backward, VMEM-blocked for the MXU; see
/opt/skills/guides/pallas_guide.md conventions) wired up as a
`jax.custom_vjp`, so it is usable inside `jax.grad` train steps. Head dims
that aren't lane-aligned (e.g. 64) are zero-padded to 128 outside the
custom_vjp — padding q/k with zeros leaves the logits unchanged and AD
slices the gradients back. On non-TPU backends it falls back to the XLA
reference implementation so the same model code runs on the CPU test mesh.

The reference framework has no attention kernels at all (it orchestrates
torch models); these exist because long-context parallelism is first-class
here (SURVEY.md §5.7).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def mha_reference(q, k, v, causal: bool = True,
                  q_offset: int = 0, k_offset: int = 0,
                  scale: Optional[float] = None):
    """XLA attention: q[B,Lq,H,D], k/v[B,Lk,Hkv,D] -> [B,Lq,H,D].
    Supports GQA (H a multiple of Hkv) and absolute position offsets for
    block-parallel callers."""
    B, Lq, H, D = q.shape
    _, Lk, Hkv, _ = k.shape
    scale = scale if scale is not None else D ** -0.5
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = jnp.arange(Lq) + q_offset
        kpos = jnp.arange(Lk) + k_offset
        mask = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(mask[None, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


# --------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                Lk: int, causal: bool, scale: float, block_q: int):
    qi = pl.program_id(1)
    q = q_ref[...]                      # [block_q, D]
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    n_kblocks = Lk // block_k

    def body(ki, carry):
        acc, m, l = carry
        k = k_ref[pl.ds(ki * block_k, block_k), :]
        v = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p.astype(v.dtype), v,
                                    preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    if causal:
        # only blocks up to (and including) the diagonal contribute
        hi = jax.lax.min(n_kblocks, (qi + 1) * block_q // block_k + 1)
    else:
        hi = n_kblocks
    acc, m, l = jax.lax.fori_loop(0, hi, body, (acc, m, l))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    lse_ref[...] = m + jnp.log(l)


# -------------------------------------------------------------- backward
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, block_k: int, Lk: int, causal: bool, scale: float,
                   block_q: int):
    qi = pl.program_id(1)
    q = q_ref[...]                          # [block_q, D]
    do = do_ref[...]
    lse = lse_ref[...]                      # [block_q, 1] f32
    delta = delta_ref[...]
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    n_kblocks = Lk // block_k

    def body(ki, acc):
        k = k_ref[pl.ds(ki * block_k, block_k), :]
        v = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp(s - lse)                # [block_q, block_k]
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return acc + jnp.dot(ds.astype(k.dtype), k,
                             preferred_element_type=jnp.float32) * scale

    if causal:
        hi = jax.lax.min(n_kblocks, (qi + 1) * block_q // block_k + 1)
    else:
        hi = n_kblocks
    acc = jax.lax.fori_loop(0, hi, body, acc)
    dq_ref[...] = acc.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_q: int, Lq: int, causal: bool,
                    scale: float, block_k: int):
    ki = pl.program_id(1)
    k = k_ref[...]                          # [block_k, D]
    v = v_ref[...]
    D = k.shape[-1]
    dk = jnp.zeros((k.shape[0], D), jnp.float32)
    dv = jnp.zeros((k.shape[0], D), jnp.float32)
    n_qblocks = Lq // block_q

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qi * block_q, block_q), :]
        do = do_ref[pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[pl.ds(qi * block_q, block_q), :]
        delta = delta_ref[pl.ds(qi * block_q, block_q), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp(s - lse)                # [block_q, block_k]
        dv = dv + jnp.dot(p.astype(do.dtype).T, do,
                          preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jnp.dot(ds.astype(q.dtype).T, q,
                          preferred_element_type=jnp.float32) * scale
        return dk, dv

    # causal: q blocks strictly before this k block contribute nothing
    lo = (ki * block_k) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(lo, n_qblocks, body, (dk, dv))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


# ------------------------------------------------- custom_vjp core (BH,L,D)
@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _flash_core(causal, block_q, block_k, scale, interpret, qf, kf, vf):
    o, _ = _flash_fwd(causal, block_q, block_k, scale, interpret,
                      qf, kf, vf)
    return o


def _flash_fwd(causal, block_q, block_k, scale, interpret, qf, kf, vf):
    BH, Lq, D = qf.shape
    _, Lk, _ = kf.shape
    kernel = functools.partial(_fwd_kernel, block_k=block_k, Lk=Lk,
                               causal=causal, scale=scale, block_q=block_q)
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, Lq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Lk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Lk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lq, D), qf.dtype),
            jax.ShapeDtypeStruct((BH, Lq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return o, (qf, kf, vf, o, lse)


def _flash_bwd(causal, block_q, block_k, scale, interpret, res, do):
    qf, kf, vf, o, lse = res
    BH, Lq, D = qf.shape
    _, Lk, _ = kf.shape
    # delta_i = rowsum(dO_i * O_i) — cheap, XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, block_k=block_k, Lk=Lk, causal=causal, scale=scale,
        block_q=block_q)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH, Lq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Lk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Lk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Lq, D), qf.dtype),
        interpret=interpret,
    )(qf, kf, vf, do, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, block_q=block_q, Lq=Lq, causal=causal, scale=scale,
        block_k=block_k)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH, Lk // block_k),
        in_specs=[
            pl.BlockSpec((None, Lq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Lq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Lq, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Lq, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lk, D), kf.dtype),
            jax.ShapeDtypeStruct((BH, Lk, D), vf.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, do, lse, delta)
    return dq, dk, dv


_flash_core.defvjp(_flash_fwd, _flash_bwd)


# ------------------------------------------------------------ public entry
def flash_attention(q, k, v, causal: bool = True, block_q: int = 256,
                    block_k: int = 256, scale: Optional[float] = None,
                    interpret: bool = False):
    """Tiled attention, differentiable. q[B,Lq,H,D], k/v[B,Lk,Hkv,D]
    (GQA ok). Head dim is zero-padded up to a multiple of 128 lanes."""
    B, Lq, H, D = q.shape
    _, Lk, Hkv, _ = k.shape
    scale = scale if scale is not None else D ** -0.5
    from ray_tpu.ops.dispatch import _on_tpu
    on_tpu = _on_tpu()
    if not (on_tpu or interpret) or Lq % 128 or Lk % 128:
        return mha_reference(q, k, v, causal=causal, scale=scale)
    block_q = min(block_q, Lq)
    block_k = min(block_k, Lk)
    if Lq % block_q or Lk % block_k or block_q % block_k:
        return mha_reference(q, k, v, causal=causal, scale=scale)
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    Dp = (D + 127) // 128 * 128
    if Dp != D:
        pad = [(0, 0), (0, 0), (0, 0), (0, Dp - D)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    # layout: [B*H, L, D] so each grid cell works on one head's q block
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, Dp)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, Dp)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, Dp)
    out = _flash_core(causal, block_q, block_k, scale, interpret,
                      qf, kf, vf)
    out = out.reshape(B, H, Lq, Dp).transpose(0, 2, 1, 3)
    return out[..., :D] if Dp != D else out
