"""Attention kernels.

`flash_attention` is a Pallas TPU kernel (tiled online-softmax attention,
VMEM-blocked for the MXU; see /opt/skills/guides/pallas_guide.md
conventions); on non-TPU backends it falls back to the XLA reference
implementation so the same model code runs on the CPU test mesh.

The reference framework has no attention kernels at all (it orchestrates
torch models); these exist because long-context parallelism is first-class
here (SURVEY.md §5.7).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def mha_reference(q, k, v, causal: bool = True,
                  q_offset: int = 0, k_offset: int = 0,
                  scale: Optional[float] = None):
    """XLA attention: q[B,Lq,H,D], k/v[B,Lk,Hkv,D] -> [B,Lq,H,D].
    Supports GQA (H a multiple of Hkv) and absolute position offsets for
    block-parallel callers."""
    B, Lq, H, D = q.shape
    _, Lk, Hkv, _ = k.shape
    scale = scale if scale is not None else D ** -0.5
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = jnp.arange(Lq) + q_offset
        kpos = jnp.arange(Lk) + k_offset
        mask = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(mask[None, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, Lk: int,
                  causal: bool, scale: float, block_q: int):
    qi = pl.program_id(1)
    q = q_ref[...]                      # [block_q, D]
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    n_kblocks = Lk // block_k

    def body(ki, carry):
        acc, m, l = carry
        k = k_ref[pl.ds(ki * block_k, block_k), :]
        v = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p.astype(v.dtype), v,
                                    preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    if causal:
        # only blocks up to (and including) the diagonal contribute
        hi = jax.lax.min(n_kblocks,
                         (qi + 1) * block_q // block_k + 1)
    else:
        hi = n_kblocks
    acc, m, l = jax.lax.fori_loop(0, hi, body, (acc, m, l))
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def flash_attention(q, k, v, causal: bool = True, block_q: int = 256,
                    block_k: int = 256, scale: Optional[float] = None,
                    interpret: bool = False):
    """Tiled attention. q[B,Lq,H,D], k/v[B,Lk,Hkv,D] (GQA ok)."""
    B, Lq, H, D = q.shape
    _, Lk, Hkv, _ = k.shape
    scale = scale if scale is not None else D ** -0.5
    from ray_tpu.ops.dispatch import _on_tpu
    on_tpu = _on_tpu()
    if not (on_tpu or interpret) or Lq % 128 or Lk % 128 or D % 128:
        return mha_reference(q, k, v, causal=causal, scale=scale)
    block_q = min(block_q, Lq)
    block_k = min(block_k, Lk)
    if Lq % block_q or Lk % block_k or block_q % block_k:
        return mha_reference(q, k, v, causal=causal, scale=scale)
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    # layout: [B*H, L, D] so each grid cell works on one head's q block
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)

    kernel = functools.partial(_flash_kernel, block_k=block_k, Lk=Lk,
                               causal=causal, scale=scale, block_q=block_q)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Lq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Lk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Lk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, Lq, D).transpose(0, 2, 1, 3)
