from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.ring_attention import ring_attention

__all__ = ["flash_attention", "mha_reference", "ring_attention"]
