"""Dataset creation (reference: python/ray/data/read_api.py —
range/from_items/read_parquet/read_csv/read_json/from_numpy/from_pandas).
Reads are lazy: each source becomes a list of zero-arg read callables
launched as tasks by the ReadStage."""

from __future__ import annotations

import glob as glob_mod
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.data import block as block_lib
from ray_tpu.data import execution as exe
from ray_tpu.data.dataset import Dataset


def range(n: int, *, parallelism: int = 8) -> Dataset:   # noqa: A001
    import builtins
    parallelism = max(1, min(parallelism, n or 1))
    per = (n + parallelism - 1) // parallelism

    def make(lo, hi):
        def read():
            import numpy as np
            import pyarrow as pa
            return pa.table({"id": np.arange(lo, hi, dtype=np.int64)})
        return read

    fns = [make(i * per, min((i + 1) * per, n))
           for i in builtins.range(parallelism) if i * per < n]
    return Dataset([exe.ReadStage(fns)])


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    import builtins
    parallelism = max(1, min(parallelism, len(items) or 1))
    per = (len(items) + parallelism - 1) // parallelism
    chunks = [items[i * per:(i + 1) * per]
              for i in builtins.range(parallelism) if i * per < len(items)]

    def make(chunk):
        return lambda: block_lib.block_from_rows(
            [r if isinstance(r, dict) else {"item": r} for r in chunk])

    return Dataset([exe.ReadStage([make(c) for c in chunks])])


def from_numpy(arr: np.ndarray, column: str = "data",
               *, parallelism: int = 8) -> Dataset:
    import builtins
    parallelism = max(1, min(parallelism, len(arr) or 1))
    splits = np.array_split(arr, parallelism)

    def make(part):
        def read():
            import pyarrow as pa
            if part.ndim == 1:
                return pa.table({column: part})
            return pa.table({column: [row.tolist() for row in part]})
        return read

    return Dataset([exe.ReadStage([make(s) for s in splits if len(s)])])


def from_pandas(df) -> Dataset:
    import pyarrow as pa
    table = pa.Table.from_pandas(df, preserve_index=False)
    return Dataset([exe.ReadStage([lambda: table])])


def from_arrow(table) -> Dataset:
    return Dataset([exe.ReadStage([lambda: table])])


def _expand_paths(paths, suffix: str) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob_mod.glob(os.path.join(p, f"*{suffix}"))))
        elif "*" in p:
            out.extend(sorted(glob_mod.glob(p)))
        else:
            out.append(p)
    return out


def read_parquet(paths, columns: Optional[List[str]] = None,
                 **kwargs) -> Dataset:
    files = _expand_paths(paths, ".parquet")

    def make(f, cols):
        def read():
            # one block per row group, streamed: a multi-row-group file
            # never buffers whole in the read worker (the streaming
            # generator's backpressure caps unconsumed blocks; reference:
            # fragment-level parquet reads,
            # _internal/datasource/parquet_datasource.py)
            import pyarrow.parquet as pq
            pf = pq.ParquetFile(f)
            if pf.metadata.num_row_groups <= 1:
                yield pf.read(columns=cols)
            else:
                # NB: builtins.range — this module defines its own
                # Dataset-returning `range`
                import builtins
                for g in builtins.range(pf.metadata.num_row_groups):
                    yield pf.read_row_group(g, columns=cols)
        read.yields_blocks = True
        # projection pushdown hook: the optimizer rebinds the read to
        # fetch only the projected columns (execution.ProjectStage)
        read.with_columns = lambda c: make(f, list(c))
        return read

    return Dataset([exe.ReadStage([make(f, columns) for f in files])])


def read_csv(paths, **kwargs) -> Dataset:
    files = _expand_paths(paths, ".csv")

    def make(f):
        def read():
            import pyarrow.csv as pcsv
            return pcsv.read_csv(f)
        return read

    return Dataset([exe.ReadStage([make(f) for f in files])])


def read_json(paths, **kwargs) -> Dataset:
    files = _expand_paths(paths, ".json")

    def make(f):
        def read():
            import pyarrow.json as pjson
            return pjson.read_json(f)
        return read

    return Dataset([exe.ReadStage([make(f) for f in files])])


def read_text(paths, **kwargs) -> Dataset:
    files = _expand_paths(paths, ".txt")

    def make(f):
        def read():
            import pyarrow as pa
            with open(f) as fh:
                lines = [line.rstrip("\n") for line in fh]
            return pa.table({"text": lines})
        return read

    return Dataset([exe.ReadStage([make(f) for f in files])])


def read_numpy(paths, *, column: str = "data", **kwargs) -> Dataset:
    """.npy files, one block per file (reference: read_numpy /
    NumpyDatasource)."""
    files = _expand_paths(paths, ".npy")

    def make(path):
        def read():
            import numpy as np
            import pyarrow as pa
            arr = np.load(path)
            if arr.ndim == 1:
                return pa.table({column: arr})
            return pa.table({column: [row.tolist() for row in arr]})
        return read

    return Dataset([exe.ReadStage([make(f) for f in files], **kwargs)])


def read_binary_files(paths, *, include_paths: bool = False,
                      suffix: str = "", **kwargs) -> Dataset:
    """Whole files as bytes rows (reference: read_binary_files /
    BinaryDatasource — the raw substrate for images/audio/etc.)."""
    files = _expand_paths(paths, suffix)

    def make(path):
        def read():
            import pyarrow as pa
            with open(path, "rb") as f:
                data = f.read()
            cols = {"bytes": [data]}
            if include_paths:
                cols["path"] = [path]
            return pa.table(cols)
        return read

    return Dataset([exe.ReadStage([make(f) for f in files], **kwargs)])


def read_images(paths, *, size=None, mode: str = "RGB",
                include_paths: bool = False, **kwargs) -> Dataset:
    """Image files decoded to arrays (reference: read_images /
    ImageDatasource; decoding via PIL when available, else raw bytes
    with a clear error)."""
    files = _expand_paths(paths, "")

    exts = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tif", ".tiff",
            ".webp")
    files = [f for f in files
             if os.path.isfile(f) and f.lower().endswith(exts)]

    def make(path):
        def read():
            import numpy as np
            try:
                from PIL import Image
            except ImportError as e:
                raise ImportError(
                    "read_images requires pillow; use read_binary_files "
                    "for raw bytes") from e
            img = Image.open(path).convert(mode)
            if size is not None:
                img = img.resize(tuple(size))
            row = {"image": np.asarray(img)}   # tensor column, unboxed
            if include_paths:
                row["path"] = path
            return block_lib.block_from_rows([row])
        return read

    return Dataset([exe.ReadStage([make(f) for f in files], **kwargs)])


def read_tfrecords(paths, **kwargs) -> Dataset:
    """TFRecord files of tf.train.Example records (reference:
    read_tfrecords / TFRecordDatasource). Parses the record framing and
    Example protos directly — no TensorFlow dependency."""
    files = _expand_paths(paths, ".tfrecord")

    def make(path):
        def read():
            import pyarrow as pa

            from ray_tpu.data import tfrecord as tfr
            rows = [tfr.example_to_row(rec)
                    for rec in tfr.read_records(path)]
            return block_lib.block_from_rows(rows) if rows else \
                pa.table({})
        return read

    return Dataset([exe.ReadStage([make(f) for f in files], **kwargs)])


def from_huggingface(dataset, *, parallelism: int = 8) -> Dataset:
    """A loaded `datasets.Dataset` (reference: from_huggingface). The
    zero-copy arrow path only applies when no lazy _indices mapping is
    pending (select/shuffle/split keep the FULL table in .data and remap
    rows lazily — reading .data directly would return the wrong rows)."""
    if getattr(dataset, "_indices", None) is None \
            and hasattr(dataset, "data"):
        table = getattr(dataset.data, "table", None)
        if table is not None:
            return from_arrow(table.combine_chunks())
    return from_items([dict(r) for r in dataset], parallelism=parallelism)

def read_sql(sql: str, connection_factory, *,
             shard_keys: Optional[List[Any]] = None,
             shard_column: Optional[str] = None) -> Dataset:
    """SQL query -> Dataset (reference: read_sql /
    _internal/datasource/sql_datasource.py). `connection_factory` is a
    zero-arg callable returning a DB-API 2.0 connection (sqlite3,
    psycopg2, ...), created INSIDE each read task so connections never
    pickle. Parallelism strategies, mirroring the reference:

    - default: one task runs the whole query (many databases cannot
      split an arbitrary query soundly);
    - shard_column + shard_keys: one task per key, appending
      ``WHERE <shard_column> = ?`` (the reference's sharded mode).
    """
    def make(where_key):
        def read():
            conn = connection_factory()
            try:
                cur = conn.cursor()
                if where_key is None:
                    cur.execute(sql)
                else:
                    # wrap as a subselect: splicing WHERE onto an
                    # arbitrary query breaks on ORDER BY/GROUP BY/LIMIT
                    # and on queries that already have a WHERE
                    cur.execute(
                        f"SELECT * FROM ({sql}) AS __rt_shard "
                        f"WHERE {shard_column} = ?", (where_key,))
                cols = [d[0] for d in cur.description]
                rows = [dict(zip(cols, r)) for r in cur.fetchall()]
            finally:
                conn.close()
            import pyarrow as pa
            return block_lib.block_from_rows(rows) if rows else pa.table({})
        return read

    if shard_keys and shard_column:
        fns = [make(k) for k in shard_keys]
    else:
        fns = [make(None)]
    return Dataset([exe.ReadStage(fns)])


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: Optional[List[Dict]] = None,
               shard_match: Optional[List[Dict]] = None,
               client_factory=None) -> Dataset:
    """MongoDB collection -> Dataset (reference: read_mongo /
    _internal/datasource/mongo_datasource.py). Connections are created
    INSIDE each read task via `client_factory` (zero-arg callable
    returning a pymongo-compatible client: ``client[db][coll]
    .aggregate(pipeline)`` yielding mapping rows) so clients never
    pickle; default factory imports pymongo, failing with a clear error
    when absent (pymongo is not bundled).

    Parallelism mirrors the reference's partitioned reads: pass
    `shard_match` = one $match filter document per shard (e.g. hash
    ranges over _id) to get one read task per shard; otherwise a single
    task streams the whole aggregation.
    """
    base = list(pipeline or [])

    def make(match):
        def read():
            if client_factory is not None:
                client = client_factory()
            else:
                try:
                    import pymongo
                except ImportError as e:
                    raise ImportError(
                        "read_mongo needs pymongo (not bundled) or an "
                        "explicit client_factory") from e
                client = pymongo.MongoClient(uri)
            try:
                pipe = ([{"$match": match}] if match else []) + base
                cursor = client[database][collection].aggregate(pipe)
                rows = []
                for doc in cursor:
                    d = dict(doc)
                    # ObjectId and friends aren't arrow types
                    if "_id" in d and not isinstance(
                            d["_id"], (str, int, float, bytes)):
                        d["_id"] = str(d["_id"])
                    rows.append(d)
            finally:
                close = getattr(client, "close", None)
                if close:
                    close()
            import pyarrow as pa
            return block_lib.block_from_rows(rows) if rows else pa.table({})
        return read

    fns = ([make(m) for m in shard_match] if shard_match
           else [make(None)])
    return Dataset([exe.ReadStage(fns)])


def read_bigquery(query: Optional[str] = None, *,
                  project_id: Optional[str] = None,
                  dataset: Optional[str] = None,
                  client_factory=None) -> Dataset:
    """BigQuery query/table -> Dataset (reference: read_bigquery /
    _internal/datasource/bigquery_datasource.py). `client_factory` is a
    zero-arg callable returning a google-cloud-bigquery-compatible
    client (``client.query(sql).result()`` yielding mapping rows),
    constructed INSIDE the read task; the default factory imports
    google.cloud.bigquery (not bundled) with a clear error. Passing
    `dataset` ("ds.table") without `query` reads the whole table, like
    the reference."""
    if query is None:
        if dataset is None:
            raise ValueError("read_bigquery needs `query` or `dataset`")
        import re
        # the name is interpolated into backtick-quoted SQL: restrict it
        # to legal BigQuery dataset/table characters so a crafted string
        # can't escape the quoting and smuggle SQL
        if not re.fullmatch(r"[A-Za-z0-9_.$-]+", dataset):
            raise ValueError(
                f"invalid BigQuery dataset name {dataset!r}: expected "
                "only letters, digits, '_', '.', '$' or '-'")
        query = f"SELECT * FROM `{dataset}`"

    def read():
        if client_factory is not None:
            client = client_factory()
        else:
            try:
                from google.cloud import bigquery
            except ImportError as e:
                raise ImportError(
                    "read_bigquery needs google-cloud-bigquery (not "
                    "bundled) or an explicit client_factory") from e
            client = bigquery.Client(project=project_id)
        rows = [dict(r) for r in client.query(query).result()]
        import pyarrow as pa
        return block_lib.block_from_rows(rows) if rows else pa.table({})

    return Dataset([exe.ReadStage([read])])


def read_webdataset(paths, *, decode: bool = True) -> Dataset:
    """WebDataset tar shards -> one row per sample (reference:
    read_webdataset / webdataset_datasource.py). Files sharing a
    basename prefix group into one sample; extensions become columns
    (`{"__key__": "sample001", "jpg": bytes|array, "cls": int, ...}`).
    Pure tarfile — no webdataset dependency; decode=True decodes
    .json/.cls/.txt (and images when PIL is present), matching the
    reference's default decoder."""
    files = _expand_paths(paths, ".tar")

    def _decode(ext: str, data: bytes):
        if not decode:
            return data
        if ext in ("cls", "index", "id"):
            return int(data)
        if ext in ("txt", "text"):
            return data.decode("utf-8")
        if ext == "json":
            import json as _json
            return _json.loads(data)
        if ext in ("jpg", "jpeg", "png"):
            try:
                import io as _io

                from PIL import Image
                return np.asarray(Image.open(_io.BytesIO(data)))
            except Exception:
                return data
        return data

    def make(path):
        def read():
            import tarfile
            samples: Dict[str, Dict[str, Any]] = {}
            order: List[str] = []
            with tarfile.open(path) as tar:
                for m in tar:
                    if not m.isfile():
                        continue
                    base, _, ext = m.name.partition(".")
                    if base not in samples:
                        samples[base] = {"__key__": base}
                        order.append(base)
                    samples[base][ext] = _decode(
                        ext, tar.extractfile(m).read())
            for key in order:
                yield block_lib.block_from_rows([samples[key]])
        read.yields_blocks = True
        return read

    return Dataset([exe.ReadStage([make(f) for f in files])])
