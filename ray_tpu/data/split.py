"""Streaming split: one executing dataset feeding N consumers (train
workers) with disjoint block streams (reference: Dataset.streaming_split
via OutputSplitter, python/ray/data/_internal/execution/operators/
output_splitter.py, wired into Train by _internal/data_config.py).

The plan executes ONCE inside a coordinator actor; consumers pull bundles
by split index over actor RPC. Block bytes never route through the
coordinator — only refs + metadata travel; consumers fetch blocks from
the object store directly.

Semantics mirrored from the reference OutputSplitter:
- bundles deal to the consumer with the fewest rows so far (row balance);
- per-consumer queues are bounded — a lagging consumer applies
  backpressure to the whole stream instead of pinning unbounded blocks;
- ``equal=True`` holds back each consumer's tail and, at end of stream,
  slices it so every consumer receives EXACTLY the same row count (the
  remainder is dropped, as in the reference) — required when consumers
  run lockstep collectives (SPMD training gangs).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, Iterator, List, Optional

import ray_tpu

_WAIT = "__wait__"          # sentinel: stream blocked on a full peer queue
_QUEUE_CAP = 16             # bundles per consumer before backpressure


class _SplitCoordinator:
    """Actor: executes the plan lazily and deals bundles row-balanced,
    preferring the consumer on the block's node when locality hints are
    given (reference: OutputSplitter.locality_hints — locality wins only
    within a bounded row-imbalance slack, so it can never starve a
    remote consumer)."""

    RETAIN = 4   # handed-out bundles pinned until the consumer's next pull

    def __init__(self, stages, n: int, equal: bool,
                 locality_hints: Optional[List[Optional[str]]] = None):
        from ray_tpu.data import execution as exe
        self._n = n
        self._equal = equal
        self._stream = iter(exe.execute_plan(stages))
        self._queues = [collections.deque() for _ in range(n)]
        self._rows_dealt = [0] * n     # rows enqueued per consumer
        self._rows_handed = [0] * n    # rows actually delivered
        # keep recently handed-out refs alive: the consumer registers its
        # borrow with us (the owner) only after deserializing the reply,
        # so dropping our copy at hand-off would free the block under it
        self._handed = [collections.deque() for _ in range(n)]
        self._hints = list(locality_hints or [])
        self._locality_hits = 0
        self._locality_total = 0
        self._pending = None     # (bundle, dest) parked on a full queue
        self._done = False
        self._trimmed = False

    def _locate(self, ref) -> Optional[str]:
        """Node id of a block this coordinator owns (cheap local read —
        the shared locality plane in ray_tpu.data.shuffle, also used for
        shuffle reduce placement)."""
        from ray_tpu.data.shuffle import object_node_ids
        return object_node_ids([ref])[0]

    def _pick_dest(self, bundle) -> int:
        balanced = min(range(self._n), key=lambda i: self._rows_dealt[i])
        if not self._hints:
            return balanced
        self._locality_total += 1
        loc = self._locate(bundle[0])
        if loc is None:
            return balanced
        local = [i for i in range(self._n) if self._hints[i] == loc]
        if not local:
            return balanced
        # locality wins within a slack of a few bundles' worth of rows;
        # beyond that, row balance takes over (a hot node must not
        # accumulate the whole stream)
        slack = 4 * max(1, bundle[1].num_rows)
        cand = min(local, key=lambda i: self._rows_dealt[i])
        if self._rows_dealt[cand] - self._rows_dealt[balanced] <= slack:
            self._locality_hits += 1
            return cand
        return balanced

    # ------------------------------------------------------------ dealing
    def _advance(self):
        """Pull one bundle from the stream and deal it. Returns True on
        progress, False at end of stream, None when blocked on a full
        queue (backpressure: the chosen consumer's full queue stalls the
        whole stream — bundles are never re-routed around a laggard,
        which would break row balance)."""
        if self._done:
            return False
        if self._pending is None:
            bundle = next(self._stream, None)
            if bundle is None:
                self._done = True
                return False
            self._pending = (bundle, self._pick_dest(bundle))
        bundle, dest = self._pending
        if len(self._queues[dest]) >= _QUEUE_CAP:
            return None
        self._pending = None
        self._queues[dest].append(bundle)
        self._rows_dealt[dest] += bundle[1].num_rows
        return True

    def _hand(self, idx: int):
        bundle = self._queues[idx].popleft()
        self._rows_handed[idx] += bundle[1].num_rows
        handed = self._handed[idx]
        handed.append(bundle)
        while len(handed) > self.RETAIN:
            handed.popleft()
        return bundle

    def _trim_for_equality(self):
        """End of stream, equal mode: pool every undelivered bundle and
        redistribute with block slicing so each consumer's total delivered
        rows is exactly the target (reference OutputSplitter's equal mode
        splits blocks and drops the remainder the same way)."""
        from ray_tpu.data import block as block_lib
        self._trimmed = True
        pool = [b for q in self._queues for b in q]
        pool_rows = sum(b[1].num_rows for b in pool)
        total = sum(self._rows_handed) + pool_rows
        # highest exactly-reachable target: nobody can hand rows back, and
        # the pool must cover everyone's deficit
        target = max(total // self._n, max(self._rows_handed))
        while target > 0 and sum(max(target - h, 0)
                                 for h in self._rows_handed) > pool_rows:
            target -= 1
        if target < max(self._rows_handed):
            # a consumer was already handed more rows than the pool can
            # match for its peers: exact equality is unreachable. Raising
            # here turns a would-be collective deadlock in lockstep SPMD
            # consumers into a loud error (_can_hand prevents this; guard
            # stays in case of a logic hole)
            raise RuntimeError(
                "streaming_split(equal=True): delivered row counts "
                f"diverged beyond repair (handed={self._rows_handed}, "
                f"undelivered pool={pool_rows} rows)")

        cursor = iter(pool)
        current = None          # (ref, meta, offset)

        def take(quota: int, out: collections.deque):
            nonlocal current
            while quota > 0:
                if current is None:
                    nxt = next(cursor, None)
                    if nxt is None:
                        return
                    current = (nxt[0], nxt[1], 0)
                ref, meta, off = current
                avail = meta.num_rows - off
                if avail <= quota and off == 0:
                    out.append((ref, meta))
                    quota -= avail
                    current = None
                else:
                    n_take = min(avail, quota)
                    block = ray_tpu.get(ref)
                    part = block_lib.slice_block(block, off, off + n_take)
                    out.append((ray_tpu.put(part),
                                block_lib.block_metadata(part)))
                    quota -= n_take
                    current = (ref, meta, off + n_take) \
                        if off + n_take < meta.num_rows else None

        for i in range(self._n):
            kept = collections.deque()
            take(max(target - self._rows_handed[i], 0), kept)
            self._queues[i] = kept

    # -------------------------------------------------------------- api
    def _can_hand(self, idx: int) -> bool:
        """Equal mode invariant: after handing the head bundle to idx, the
        undelivered pool must still cover every peer's deficit to the new
        max — bounding run-ahead by ROWS (a fixed bundle-depth reserve
        lets uneven block sizes silently break exact equality)."""
        rows = self._queues[idx][0][1].num_rows
        pool = sum(b[1].num_rows for q in self._queues for b in q) - rows
        handed = list(self._rows_handed)
        handed[idx] += rows
        hmax = max(handed)
        return sum(hmax - h for h in handed) <= pool

    def next(self, idx: int):
        """Next (block_ref, metadata) for consumer idx; (_WAIT,) when the
        stream is backpressured by a lagging peer; None at end."""
        q = self._queues[idx]
        while True:
            if self._equal and not self._done:
                # keep one bundle in reserve until the stream ends so the
                # tail can be sliced to equality
                if len(q) >= 2 and self._can_hand(idx):
                    return self._hand(idx)
            elif q:
                return self._hand(idx)
            progressed = self._advance()
            if progressed is None:
                return (_WAIT,) if not q or self._equal else self._hand(idx)
            if progressed is False:
                if self._equal and not self._trimmed:
                    self._trim_for_equality()
                    q = self._queues[idx]
                return self._hand(idx) if q else None

    def rows_delivered(self) -> List[int]:
        return list(self._rows_handed)

    def locality_stats(self):
        """(locality_hits, bundles_dealt_with_hints) — observability for
        the locality-aware dealing path."""
        return (self._locality_hits, self._locality_total)

    def ping(self):
        return True


class DataIterator:
    """Per-consumer shard handle; usable from any process holding it
    (reference: ray.data.DataIterator returned by streaming_split)."""

    def __init__(self, coordinator, idx: int):
        self._coordinator = coordinator
        self._idx = idx

    def _bundles(self) -> Iterator:
        while True:
            bundle = ray_tpu.get(
                self._coordinator.next.remote(self._idx), timeout=600)
            if bundle is None:
                return
            if bundle[0] == _WAIT:
                time.sleep(0.1)
                continue
            yield tuple(bundle)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False):
        from ray_tpu.data import iterator as it
        return it.iter_batches(self._bundles(), batch_size=batch_size,
                               batch_format=batch_format,
                               drop_last=drop_last)

    def iter_jax_batches(self, **kw):
        from ray_tpu.data import iterator as it
        return it.iter_jax_batches(self._bundles(), **kw)

    def iter_rows(self):
        from ray_tpu.data import block as B
        for ref, _meta in self._bundles():
            yield from B.block_to_rows(ray_tpu.get(ref))


def streaming_split(dataset, n: int, *, equal: bool = False,
                    locality_hints=None) -> List[DataIterator]:
    """Split `dataset`'s output stream across n consumers.
    ``locality_hints``: optional node id per consumer — bundles whose
    block already lives on a hinted node deal to that consumer (within a
    bounded row-imbalance slack), so train workers read their shards
    from local shm instead of pulling cross-node (reference:
    OutputSplitter locality_hints via actor node ids)."""
    if locality_hints is not None and len(locality_hints) != n:
        raise ValueError(
            f"locality_hints must have one entry per consumer: got "
            f"{len(locality_hints)} hints for n={n}")
    coord_cls = ray_tpu.remote(num_cpus=0.1)(_SplitCoordinator)
    coord = coord_cls.remote(dataset._stages, n, equal, locality_hints)
    ray_tpu.get(coord.ping.remote(), timeout=120)
    return [DataIterator(coord, i) for i in range(n)]
