"""Distributed map/reduce exchange for all-to-all Data ops (reference:
python/ray/data/_internal/planner/exchange/ — ShuffleTaskSpec,
SortTaskSpec; push-based map/reduce through the object store).

Shape: every input block runs a PARTITION task (num_returns = n_reduce)
that splits it into reduce partitions; every output partition runs a
REDUCE task over its column of the ref matrix. Only refs flow through the
driver — block bytes move node-to-node via the object store's push-based
transfer, so per-node memory is bounded by the blocks a task touches, not
the dataset."""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data import block as block_lib


# ------------------------------------------------------------- partition fns
def partition_random(block, n: int, seed):
    import numpy as np
    rng = np.random.default_rng(seed)
    if block.num_rows == 0:
        return [block] * n
    assign = rng.integers(0, n, size=block.num_rows)
    return [block.take(np.nonzero(assign == j)[0]) for j in range(n)]


def partition_round_robin(block, n: int):
    """Row-cyclic split into n near-equal partitions (the streaming
    repartition map fn: no global row offset is needed, so it works on a
    stream; each output partition ends up within one row of balance per
    input block)."""
    import numpy as np
    if block.num_rows == 0:
        return [block] * n
    idx = np.arange(block.num_rows) % n
    return [block.take(np.nonzero(idx == j)[0]) for j in range(n)]


def _stable_hash(v) -> int:
    """Process-independent hash. Python's builtin hash() of str/bytes is
    salted per interpreter (PYTHONHASHSEED), so two partition tasks on
    different workers would route the same key to different partitions,
    breaking the key-disjointness invariant reduce_agg/reduce_map_groups
    rely on. crc32 over a repr-stable byte encoding is deterministic
    everywhere."""
    import zlib

    import numpy as np
    # canonicalize numerics first: pandas materializes int columns as
    # np.int64 (or float64 when the block has nulls), so 5, np.int64(5)
    # and 5.0 must hash identically or the same key routes to different
    # partitions from different blocks
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, float) and not isinstance(v, bool) and v.is_integer():
        v = int(v)
    if isinstance(v, bytes):
        b = v
    elif isinstance(v, str):
        b = v.encode("utf-8", "surrogatepass")
    elif isinstance(v, bool):
        b = b"\x01" if v else b"\x00"
    elif isinstance(v, int):
        b = v.to_bytes((v.bit_length() + 8) // 8 + 1, "little", signed=True)
    elif isinstance(v, float):
        import struct
        b = struct.pack("<d", v)
    elif v is None:
        b = b"\xff"
    else:
        b = repr(v).encode("utf-8", "surrogatepass")
    return zlib.crc32(b)


def partition_hash(block, key: str, n: int):
    import numpy as np
    if block.num_rows == 0:
        return [block] * n
    col = block.column(key).to_pandas()
    part = np.asarray(col.map(lambda v: _stable_hash(v) % n), np.int64)
    return [block.take(np.nonzero(part == j)[0]) for j in range(n)]


def partition_range(block, key: str, bounds: List, descending: bool):
    """Split by sorted range boundaries (len(bounds) + 1 partitions)."""
    import numpy as np
    n = len(bounds) + 1
    if block.num_rows == 0:
        return [block] * n
    col = np.asarray(block.column(key).to_pandas())
    idx = np.searchsorted(np.asarray(bounds), col, side="right")
    if descending:
        idx = (n - 1) - idx
    return [block.take(np.nonzero(idx == j)[0]) for j in range(n)]


# --------------------------------------------------------------- reduce fns
def reduce_concat(seed, *parts):
    import numpy as np
    merged = block_lib.concat_blocks(list(parts))
    if seed is not None and merged.num_rows:
        rng = np.random.default_rng(seed)
        merged = merged.take(rng.permutation(merged.num_rows))
    return merged


def reduce_sorted(key, descending, *parts):
    merged = block_lib.concat_blocks(list(parts))
    if merged.num_rows == 0:
        return merged    # all-empty concat loses the schema; don't sort
    order = "descending" if descending else "ascending"
    return merged.sort_by([(key, order)])


def reduce_agg(key, aggs, *parts):
    """Per-partition arrow group-by aggregate (keys are hash-disjoint
    across partitions, so no cross-partition combine is needed)."""
    merged = block_lib.concat_blocks(list(parts))
    if merged.num_rows == 0:
        return merged
    spec = [(c, f) for c, f, _ in aggs]
    out = merged.group_by(key).aggregate(spec)
    rename = {f"{c}_{f}": name for c, f, name in aggs}
    return out.rename_columns(
        [rename.get(c, c) for c in out.column_names])


def reduce_map_groups(key, fn, *parts):
    import pandas as pd
    merged = block_lib.concat_blocks(list(parts))
    if merged.num_rows == 0:
        return merged
    df = merged.to_pandas()
    outs = [fn(g) for _, g in df.groupby(key, sort=False)]
    outs = [o if isinstance(o, pd.DataFrame) else pd.DataFrame(o)
            for o in outs]
    return block_lib.block_from_batch(pd.concat(outs)) if outs \
        else merged.slice(0, 0)


# ------------------------------------------------------------------- driver
def exchange(refs: List, n_reduce: int, partition_fn: Callable,
             partition_args: tuple, reduce_fn: Callable,
             reduce_args: tuple) -> Iterator[Tuple]:
    """Run the two-phase exchange; yields (block_ref, metadata) bundles.
    Blocks never materialize in the driver — reduce tasks return their
    block AND metadata, and only the metadata is fetched here."""
    n_reduce = max(1, n_reduce)

    def _part(block, *args):
        return tuple(partition_fn(block, *args))

    def _reduce(*parts):
        out = reduce_fn(*reduce_args, *parts)
        return out, block_lib.block_metadata(out)

    part_task = ray_tpu.remote(_part).options(num_returns=n_reduce)
    reduce_task = ray_tpu.remote(_reduce).options(num_returns=2)

    matrix = []     # matrix[i][j]: map i's piece of reduce partition j
    for ref in refs:
        out = part_task.remote(ref, *partition_args)
        matrix.append(out if isinstance(out, list) else [out])
    for j in range(n_reduce):
        block_ref, meta_ref = reduce_task.remote(
            *[row[j] for row in matrix])
        meta = ray_tpu.get(meta_ref)
        if meta.num_rows:
            yield (block_ref, meta)


def sample_sort_bounds(refs: List, key: str, n: int,
                       sample_size: int = 256) -> List:
    """Approximate range boundaries from per-block samples (reference:
    SortTaskSpec.sample_boundaries)."""
    import numpy as np

    def _sample(block):
        if block.num_rows == 0:
            return []
        col = np.asarray(block.column(key).to_pandas())
        k = min(sample_size, len(col))
        idx = np.random.default_rng(0).choice(len(col), size=k,
                                              replace=False)
        return col[idx].tolist()

    sample_task = ray_tpu.remote(_sample)
    samples = [v for ref in refs
               for v in ray_tpu.get(sample_task.remote(ref))]
    if not samples:
        return []
    samples.sort()
    return [samples[int(len(samples) * (j + 1) / n)]
            for j in range(n - 1)
            if int(len(samples) * (j + 1) / n) < len(samples)]
