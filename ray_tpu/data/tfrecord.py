"""Minimal TFRecord + tf.train.Example codec, no TensorFlow dependency
(reference: python/ray/data/_internal/datasource/tfrecords_datasource.py —
that one parses with TF/protobuf; this is a self-contained wire-format
implementation: TFRecord framing with masked crc32c, and the tiny protobuf
subset Example actually uses).

Wire format per record:
    uint64 length (LE) | uint32 masked_crc32c(length bytes) |
    payload | uint32 masked_crc32c(payload)

Example proto subset:
    Example      := field 1 (Features)
    Features     := repeated field 1 (map entry: key=str, value=Feature)
    Feature      := oneof field 1 BytesList / 2 FloatList / 3 Int64List
    BytesList    := repeated field 1 bytes
    FloatList    := repeated field 1 float (packed)
    Int64List    := repeated field 1 varint (packed)
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

# ------------------------------------------------------------------ crc32c
_CRC_TABLE = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
    _CRC_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (_CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)) & 0xFFFFFFFF
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ----------------------------------------------------------------- framing
def read_records(path: str, *, validate: bool = False) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                if validate:
                    raise ValueError(f"truncated record header in {path}")
                return
            (length,) = struct.unpack("<Q", header[:8])
            payload = f.read(length)
            pcrc_raw = f.read(4)
            if len(payload) < length or len(pcrc_raw) < 4:
                if validate:
                    raise ValueError(f"truncated record in {path}")
                return
            if validate:
                (hcrc,) = struct.unpack("<I", header[8:])
                if _masked_crc(header[:8]) != hcrc:
                    raise ValueError(f"corrupt record header in {path}")
                (pcrc,) = struct.unpack("<I", pcrc_raw)
                if _masked_crc(payload) != pcrc:
                    raise ValueError(f"corrupt record payload in {path}")
            yield payload


def write_records(path: str, payloads: List[bytes]) -> None:
    with open(path, "wb") as f:
        for payload in payloads:
            header = struct.pack("<Q", len(payload))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(payload)
            f.write(struct.pack("<I", _masked_crc(payload)))


# ------------------------------------------------------------- proto codec
def _read_varint(data: bytes, i: int):
    out = shift = 0
    while True:
        b = data[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _fields(data: bytes) -> Iterator[tuple]:
    """(field_number, wire_type, value) over a serialized message."""
    i = 0
    while i < len(data):
        tag, i = _read_varint(data, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(data, i)
        elif wt == 2:
            ln, i = _read_varint(data, i)
            v = data[i:i + ln]
            i += ln
        elif wt == 5:
            v = data[i:i + 4]
            i += 4
        elif wt == 1:
            v = data[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _decode_feature(data: bytes):
    for field, wt, v in _fields(data):
        if field == 1:      # BytesList
            return [bv for f2, _, bv in _fields(v) if f2 == 1]
        if field == 2:      # FloatList (packed or repeated)
            floats: List[float] = []
            for f2, wt2, fv in _fields(v):
                if f2 != 1:
                    continue
                if wt2 == 2:
                    floats.extend(struct.unpack(f"<{len(fv) // 4}f", fv))
                else:
                    floats.append(struct.unpack("<f", fv)[0])
            return floats
        if field == 3:      # Int64List
            ints: List[int] = []
            for f2, wt2, iv in _fields(v):
                if f2 != 1:
                    continue
                if wt2 == 2:
                    j = 0
                    while j < len(iv):
                        n, j = _read_varint(iv, j)
                        ints.append(n - (1 << 64) if n >= 1 << 63 else n)
                else:
                    ints.append(iv - (1 << 64) if iv >= 1 << 63 else iv)
            return ints
    return []


def example_to_row(payload: bytes) -> Dict[str, Any]:
    """Serialized tf.train.Example -> {column: scalar-or-list}."""
    row: Dict[str, Any] = {}
    for field, _, features in _fields(payload):
        if field != 1:
            continue
        for f2, _, entry in _fields(features):
            if f2 != 1:
                continue
            key = None
            value = None
            for f3, _, v in _fields(entry):
                if f3 == 1:
                    key = v.decode()
                elif f3 == 2:
                    value = _decode_feature(v)
            if key is not None:
                if isinstance(value, list) and len(value) == 1:
                    value = value[0]
                if isinstance(value, bytes):
                    try:
                        value = value.decode()
                    except UnicodeDecodeError:
                        pass
                row[key] = value
    return row


def _encode_feature(values) -> bytes:
    import numpy as np
    # normalize numpy scalars so dtype quirks can't flip the branch
    values = [v.item() if isinstance(v, np.generic) else v for v in values]
    inner = bytearray()
    if values and isinstance(values[0], (bytes, str)):
        for v in values:
            b = v.encode() if isinstance(v, str) else v
            inner.append((1 << 3) | 2)
            _write_varint(inner, len(b))
            inner += b
        kind = 1
    elif values and isinstance(values[0], float):
        packed = struct.pack(f"<{len(values)}f", *values)
        inner.append((1 << 3) | 2)
        _write_varint(inner, len(packed))
        inner += packed
        kind = 2
    else:
        packed = bytearray()
        for v in values:
            _write_varint(packed, v & ((1 << 64) - 1))
        inner.append((1 << 3) | 2)
        _write_varint(inner, len(packed))
        inner += packed
        kind = 3
    out = bytearray()
    out.append((kind << 3) | 2)
    _write_varint(out, len(inner))
    out += inner
    return bytes(out)


def row_to_example(row: Dict[str, Any]) -> bytes:
    """{column: scalar-or-list} -> serialized tf.train.Example."""
    entries = bytearray()
    for key, value in row.items():
        values = value if isinstance(value, (list, tuple)) else [value]
        kb = key.encode()
        feat = _encode_feature(list(values))
        entry = bytearray()
        entry.append((1 << 3) | 2)
        _write_varint(entry, len(kb))
        entry += kb
        entry.append((2 << 3) | 2)
        _write_varint(entry, len(feat))
        entry += feat
        entries.append((1 << 3) | 2)
        _write_varint(entries, len(entry))
        entries += entry
    out = bytearray()
    out.append((1 << 3) | 2)
    _write_varint(out, len(entries))
    out += entries
    return bytes(out)
