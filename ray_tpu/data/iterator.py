"""Batch iteration, including the device-feed path.

`iter_jax_batches` is the TPU-first replacement for the reference's
iter_torch_batches (reference: python/ray/data/iterator.py,
block_batching/): batches prefetch on a background thread and are placed
onto the mesh with jax.device_put against the requested sharding, so
host→HBM transfer overlaps the training step (the "ingest feeds device
buffers" north-star)."""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterator, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_lib


def _batches_of(bundles, batch_size: Optional[int], batch_format: str,
                drop_last: bool):
    """Re-chunk a stream of blocks into exact-size batches."""
    buffer = []
    buffered_rows = 0
    for ref, meta in bundles:
        block = ray_tpu.get(ref)
        if block.num_rows == 0:
            continue
        if batch_size is None:
            yield block_lib.block_to_batch(block, batch_format)
            continue
        buffer.append(block)
        buffered_rows += block.num_rows
        while buffered_rows >= batch_size:
            merged = block_lib.concat_blocks(buffer)
            out = block_lib.slice_block(merged, 0, batch_size)
            rest = block_lib.slice_block(merged, batch_size,
                                         merged.num_rows)
            yield block_lib.block_to_batch(out, batch_format)
            buffer = [rest] if rest.num_rows else []
            buffered_rows = rest.num_rows
    if buffer and not drop_last and batch_size is not None:
        merged = block_lib.concat_blocks(buffer)
        if merged.num_rows:
            yield block_lib.block_to_batch(merged, batch_format)


def iter_batches(bundles, *, batch_size: Optional[int], batch_format: str,
                 drop_last: bool = False):
    yield from _batches_of(bundles, batch_size, batch_format, drop_last)


def iter_jax_batches(bundles, *, batch_size: int, mesh=None, sharding=None,
                     drop_last: bool = True, prefetch: int = 2,
                     device_prefetch: int = 2,
                     dtypes: Optional[Dict] = None):
    """Yields dict-of-jax-arrays batches placed per `sharding` (or
    replicated batch-sharded over the mesh's data axes when only `mesh`
    is given). Two overlap layers feed the mesh (the "ingest feeds TPU
    device buffers" north star):
    - a prefetch thread overlaps host batch prep (block fetch, slicing,
      dtype casts) with everything downstream;
    - a depth-`device_prefetch` buffer of already-device_put batches keeps
      host->HBM DMA running while the training step consumes the previous
      batch (jax.device_put is async, so enqueueing N batches ahead
      overlaps transfer with compute)."""
    import collections

    import jax

    if sharding is None and mesh is not None:
        from ray_tpu.parallel.sharding import batch_sharding
        sharding = batch_sharding(mesh, with_seq=False)

    q: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
    SENTINEL = object()
    err: list = []

    def producer():
        try:
            for batch in _batches_of(bundles, batch_size, "numpy",
                                     drop_last):
                if dtypes:
                    batch = {k: np.asarray(v, dtypes.get(k, v.dtype))
                             for k, v in batch.items()}
                q.put(batch)
        except BaseException as e:      # surfaced to the consumer
            err.append(e)
        finally:
            q.put(SENTINEL)

    t = threading.Thread(target=producer, daemon=True)
    t.start()

    def to_device(item):
        if sharding is not None:
            return {k: jax.device_put(v, sharding) for k, v in item.items()}
        return {k: jax.numpy.asarray(v) for k, v in item.items()}

    pending = collections.deque()
    depth = max(1, device_prefetch)
    while True:
        item = q.get()
        if item is SENTINEL:
            break
        pending.append(to_device(item))
        if len(pending) >= depth:
            yield pending.popleft()
    while pending:
        yield pending.popleft()
    if err:
        raise err[0]
