"""Preprocessors: fit/transform feature pipelines over Datasets
(reference: python/ray/data/preprocessors/ — StandardScaler, MinMaxScaler,
LabelEncoder, OneHotEncoder, Concatenator, Chain; fit computes dataset
statistics with the distributed aggregates, transform is a map_batches)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Preprocessor:
    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted:
            raise RuntimeError(f"{type(self).__name__} not fitted")
        return self._transform(ds)

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def _fit(self, ds):
        raise NotImplementedError

    def _transform(self, ds):
        raise NotImplementedError


class StandardScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats: Dict[str, tuple] = {}

    def _fit(self, ds):
        for c in self.columns:
            self.stats[c] = (ds.mean(c), max(ds.std(c, ddof=0), 1e-12))

    def _transform(self, ds):
        stats = dict(self.stats)

        def scale(df):
            df = df.copy()
            for c, (mu, sd) in stats.items():
                df[c] = (df[c] - mu) / sd
            return df
        return ds.map_batches(scale, batch_format="pandas")


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats: Dict[str, tuple] = {}

    def _fit(self, ds):
        for c in self.columns:
            lo, hi = ds.min(c), ds.max(c)
            self.stats[c] = (lo, max(hi - lo, 1e-12))

    def _transform(self, ds):
        stats = dict(self.stats)

        def scale(df):
            df = df.copy()
            for c, (lo, rng) in stats.items():
                df[c] = (df[c] - lo) / rng
            return df
        return ds.map_batches(scale, batch_format="pandas")


class LabelEncoder(Preprocessor):
    def __init__(self, label_column: str):
        self.label_column = label_column
        self.mapping: Dict = {}

    def _fit(self, ds):
        self.mapping = {v: i for i, v in
                        enumerate(sorted(ds.unique(self.label_column)))}

    def _transform(self, ds):
        col, mapping = self.label_column, dict(self.mapping)

        def enc(df):
            df = df.copy()
            df[col] = df[col].map(mapping)
            return df
        return ds.map_batches(enc, batch_format="pandas")


class OneHotEncoder(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.categories: Dict[str, List] = {}

    def _fit(self, ds):
        for c in self.columns:
            self.categories[c] = sorted(ds.unique(c))

    def _transform(self, ds):
        cats = {c: list(v) for c, v in self.categories.items()}

        def enc(df):
            df = df.copy()
            for c, values in cats.items():
                for v in values:
                    df[f"{c}_{v}"] = (df[c] == v).astype(np.int64)
                df = df.drop(columns=[c])
            return df
        return ds.map_batches(enc, batch_format="pandas")


class Concatenator(Preprocessor):
    """Concatenate feature columns into one vector column."""

    def __init__(self, columns: List[str], output_column_name: str = "features"):
        self.columns = list(columns)
        self.output = output_column_name

    def _fit(self, ds):
        pass

    def _transform(self, ds):
        cols, out = list(self.columns), self.output

        def cat(batch):
            import pandas as pd
            stacked = np.stack([batch[c].to_numpy() for c in cols], axis=1)
            rest = batch.drop(columns=cols)
            rest[out] = list(stacked)
            return rest
        return ds.map_batches(cat, batch_format="pandas")


class Chain(Preprocessor):
    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def fit(self, ds):
        for p in self.preprocessors:
            ds = p.fit_transform(ds)
        self._fitted = True
        return self

    def _transform(self, ds):
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds
