"""Dataset: lazy, streaming distributed datasets (reference:
python/ray/data/dataset.py — logical plan of operations executed by the
streaming executor on materialization/iteration)."""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Iterator, List, Optional

import ray_tpu
from ray_tpu.data import block as block_lib
from ray_tpu.data import execution as exe


class Dataset:
    def __init__(self, stages: List[exe.Stage]):
        self._stages = stages
        self._materialized: Optional[List[exe.RefBundle]] = None

    # ------------------------------------------------------------ transforms
    def _extend(self, stage: exe.Stage) -> "Dataset":
        return Dataset(self._stages + [stage])

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    fn_args=(), fn_kwargs=None,
                    concurrency: Optional[int] = None,
                    **_ignored) -> "Dataset":
        return self._extend(exe.MapStage("map_batches", fn,
                                         batch_format=batch_format,
                                         fn_args=fn_args,
                                         fn_kwargs=fn_kwargs,
                                         concurrency=concurrency))

    def map(self, fn: Callable, *, concurrency=None, **_) -> "Dataset":
        return self._extend(exe.MapStage("map", fn, concurrency=concurrency))

    def filter(self, fn: Callable, *, concurrency=None, **_) -> "Dataset":
        return self._extend(exe.MapStage("filter", fn,
                                         concurrency=concurrency))

    def flat_map(self, fn: Callable, *, concurrency=None, **_) -> "Dataset":
        return self._extend(exe.MapStage("flat_map", fn,
                                         concurrency=concurrency))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._extend(exe.AllToAllStage("repartition",
                                              num_blocks=num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._extend(exe.AllToAllStage("random_shuffle", seed=seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._extend(exe.AllToAllStage("sort", key=key,
                                              descending=descending))

    def limit(self, n: int) -> "Dataset":
        return self._extend(exe.LimitStage(n))

    def union(self, *others: "Dataset") -> "Dataset":
        bundles = list(self._execute())
        for o in others:
            bundles.extend(o._execute())
        return Dataset([exe.InputStage(bundles)])

    # ------------------------------------------------------------- execution
    def _execute(self) -> Iterator[exe.RefBundle]:
        if self._materialized is not None:
            return iter(self._materialized)
        return exe.execute_plan(self._stages)

    def materialize(self) -> "Dataset":
        bundles = list(self._execute())
        ds = Dataset([exe.InputStage(bundles)])
        ds._materialized = bundles
        return ds

    def get_internal_block_refs(self) -> List:
        return [r for r, _ in self._execute()]

    # ----------------------------------------------------------- consumption
    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 1,
                     drop_last: bool = False):
        from ray_tpu.data.iterator import iter_batches as _ib
        return _ib(self._execute(), batch_size=batch_size,
                   batch_format=batch_format, drop_last=drop_last)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref, _ in self._execute():
            yield from block_lib.block_to_rows(ray_tpu.get(ref))

    def iter_jax_batches(self, *, batch_size: int, mesh=None, sharding=None,
                         batch_format: str = "numpy", drop_last: bool = True,
                         prefetch: int = 2, dtypes=None):
        from ray_tpu.data.iterator import iter_jax_batches as _ijb
        return _ijb(self._execute(), batch_size=batch_size, mesh=mesh,
                    sharding=sharding, drop_last=drop_last,
                    prefetch=prefetch, dtypes=dtypes)

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(meta.num_rows for _, meta in self._execute())

    def schema(self):
        for ref, meta in self._execute():
            if meta.schema is not None:
                return meta.schema
        return None

    def num_blocks(self) -> int:
        return len(list(self._execute()))

    def to_pandas(self):
        blocks = [ray_tpu.get(r) for r, _ in self._execute()]
        return block_lib.concat_blocks(blocks).to_pandas()

    def split(self, n: int) -> List["Dataset"]:
        bundles = list(self._execute())
        shards: List[List[exe.RefBundle]] = [[] for _ in range(n)]
        # greedy row balancing
        order = sorted(bundles, key=lambda b: -b[1].num_rows)
        sizes = [0] * n
        for b in order:
            i = sizes.index(min(sizes))
            shards[i].append(b)
            sizes[i] += b[1].num_rows
        return [Dataset([exe.InputStage(s)]) for s in shards]

    # ---------------------------------------------------------------- writes
    def write_parquet(self, path: str):
        import os
        import pyarrow.parquet as pq
        os.makedirs(path, exist_ok=True)
        for i, (ref, _) in enumerate(self._execute()):
            pq.write_table(ray_tpu.get(ref),
                           os.path.join(path, f"part-{i:05d}.parquet"))

    def write_csv(self, path: str):
        import os
        import pyarrow.csv as pcsv
        os.makedirs(path, exist_ok=True)
        for i, (ref, _) in enumerate(self._execute()):
            pcsv.write_csv(ray_tpu.get(ref),
                           os.path.join(path, f"part-{i:05d}.csv"))

    def __repr__(self):
        return f"Dataset(stages={len(self._stages)})"
