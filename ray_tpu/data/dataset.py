"""Dataset: lazy, streaming distributed datasets (reference:
python/ray/data/dataset.py — logical plan of operations executed by the
streaming executor on materialization/iteration)."""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Iterator, List, Optional

import ray_tpu
from ray_tpu.data import block as block_lib
from ray_tpu.data import execution as exe
from ray_tpu.data import shuffle as shuffle_lib


class Dataset:
    def __init__(self, stages: List[exe.Stage]):
        self._stages = stages
        self._materialized: Optional[List[exe.RefBundle]] = None
        self._last_stats: Optional[exe.ExecutionStats] = None

    # ------------------------------------------------------------ transforms
    def _extend(self, stage: exe.Stage) -> "Dataset":
        return Dataset(self._stages + [stage])

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    fn_args=(), fn_kwargs=None,
                    fn_constructor_args=(), fn_constructor_kwargs=None,
                    concurrency: Optional[int] = None,
                    num_cpus: Optional[float] = None,
                    **_ignored) -> "Dataset":
        if isinstance(fn, type):
            # callable class -> stateful transform on an actor pool
            # (reference: ActorPoolMapOperator; map_batches(CallableCls,
            # concurrency=N) in ray.data)
            return self._extend(exe.ActorPoolMapStage(
                fn, batch_format=batch_format,
                fn_constructor_args=fn_constructor_args,
                fn_constructor_kwargs=fn_constructor_kwargs,
                fn_args=fn_args, fn_kwargs=fn_kwargs,
                pool_size=concurrency or 2,
                num_cpus=0.5 if num_cpus is None else num_cpus))
        return self._extend(exe.MapStage("map_batches", fn,
                                         batch_format=batch_format,
                                         fn_args=fn_args,
                                         fn_kwargs=fn_kwargs,
                                         concurrency=concurrency,
                                         num_cpus=num_cpus))

    def map(self, fn: Callable, *, concurrency=None, **_) -> "Dataset":
        return self._extend(exe.MapStage("map", fn, concurrency=concurrency))

    def filter(self, fn: Callable, *, concurrency=None, **_) -> "Dataset":
        return self._extend(exe.MapStage("filter", fn,
                                         concurrency=concurrency))

    def flat_map(self, fn: Callable, *, concurrency=None, **_) -> "Dataset":
        return self._extend(exe.MapStage("flat_map", fn,
                                         concurrency=concurrency))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._extend(shuffle_lib.ShuffleStage(
            "repartition", num_blocks=num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_partitions: Optional[int] = None) -> "Dataset":
        """Streaming push-based shuffle: inputs are consumed and
        partitioned incrementally, so memory stays bounded by the
        in-flight window + object-store spill, not the dataset size
        (ray_tpu.data.shuffle)."""
        return self._extend(shuffle_lib.ShuffleStage(
            "random_shuffle", seed=seed, num_partitions=num_partitions))

    def sort(self, key: str, descending: bool = False, *,
             num_partitions: Optional[int] = None) -> "Dataset":
        return self._extend(shuffle_lib.ShuffleStage(
            "sort", key=key, descending=descending,
            num_partitions=num_partitions))

    def limit(self, n: int) -> "Dataset":
        return self._extend(exe.LimitStage(n))

    # ------------------------------------------------------------ column ops
    def add_column(self, name: str, fn: Callable) -> "Dataset":
        """fn(pandas.DataFrame) -> column values (reference:
        Dataset.add_column)."""
        def _add(df):
            df = df.copy()
            df[name] = fn(df)
            return df
        return self.map_batches(_add, batch_format="pandas")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(lambda df: df.drop(columns=list(cols)),
                                batch_format="pandas")

    def select_columns(self, cols: List[str]) -> "Dataset":
        # first-class ProjectStage: the optimizer pushes it into
        # column-prunable reads (execution._pushdown_projection)
        return self._extend(exe.ProjectStage(cols))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self.map_batches(lambda df: df.rename(columns=dict(mapping)),
                                batch_format="pandas")

    # ---------------------------------------------------------------- groupby
    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # ------------------------------------------------------ global aggregates
    def _column_chunks(self, col: str):
        import numpy as np
        for ref, _ in self._execute():
            block = ray_tpu.get(ref)
            if block.num_rows:
                yield np.asarray(block.column(col).to_numpy(
                    zero_copy_only=False))

    def sum(self, col: str):
        return float(__import__("numpy").sum(
            [c.sum() for c in self._column_chunks(col)]))

    def min(self, col: str):
        return float(min(c.min() for c in self._column_chunks(col)))

    def max(self, col: str):
        return float(max(c.max() for c in self._column_chunks(col)))

    def mean(self, col: str):
        import numpy as np
        tot, n = 0.0, 0
        for c in self._column_chunks(col):
            tot += float(c.sum())
            n += c.size
        return tot / max(n, 1)

    def std(self, col: str, ddof: int = 1):
        import numpy as np
        chunks = list(self._column_chunks(col))
        if not chunks:
            return 0.0
        all_ = np.concatenate(chunks)
        return float(np.std(all_, ddof=ddof))

    def unique(self, col: str) -> List:
        import numpy as np
        seen = []
        s = set()
        for c in self._column_chunks(col):
            for v in np.unique(c):
                v = v.item() if hasattr(v, "item") else v
                if v not in s:
                    s.add(v)
                    seen.append(v)
        return seen

    # ------------------------------------------------------------ splits/zip
    def random_split(self, fractions: List[float],
                     seed: Optional[int] = None) -> List["Dataset"]:
        import numpy as np
        rows = self.take_all()
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(rows))
        out = []
        start = 0
        from ray_tpu.data.read_api import from_items
        for f in fractions:
            k = int(round(f * len(rows)))
            out.append(from_items([rows[i] for i in idx[start:start + k]]))
            start += k
        return out

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of equal-length datasets (reference:
        Dataset.zip; clashing names get a _1 suffix)."""
        import pandas as pd
        a = self.to_pandas()
        b = other.to_pandas()
        if len(a) != len(b):
            raise ValueError(f"length mismatch: {len(a)} vs {len(b)}")
        b = b.rename(columns={c: f"{c}_1" for c in b.columns
                              if c in a.columns})
        from ray_tpu.data.read_api import from_pandas
        return from_pandas(pd.concat([a.reset_index(drop=True),
                                      b.reset_index(drop=True)], axis=1))

    def union(self, *others: "Dataset") -> "Dataset":
        bundles = list(self._execute())
        for o in others:
            bundles.extend(o._execute())
        return Dataset([exe.InputStage(bundles)])

    # ------------------------------------------------------------- execution
    def _execute(self) -> Iterator[exe.RefBundle]:
        if self._materialized is not None:
            return iter(self._materialized)
        self._last_stats = exe.ExecutionStats()
        return exe.execute_plan(self._stages, stats=self._last_stats)

    def stats(self) -> str:
        """Per-operator execution metrics (tasks/rows/bytes/wall) for the
        most recent execution — runs the plan if it never executed
        (reference: Dataset.stats(), _internal/stats.py)."""
        if self._materialized is not None:
            if self._last_stats is not None:
                return self._last_stats.summary()
            st = exe.StageStats("Input")
            for _, meta in self._materialized:
                st.tasks += 1
                st.rows += getattr(meta, "num_rows", 0) or 0
                st.bytes += getattr(meta, "size_bytes", 0) or 0
            st.done = True
            stats = exe.ExecutionStats()
            stats.stages.append(st)
            self._last_stats = stats
            return stats.summary()
        if self._last_stats is None or not all(
                s.done for s in self._last_stats.stages):
            for _ in self._execute():
                pass
        return self._last_stats.summary()

    def materialize(self) -> "Dataset":
        bundles = list(self._execute())
        ds = Dataset([exe.InputStage(bundles)])
        ds._materialized = bundles
        ds._last_stats = self._last_stats   # stats of the producing run
        return ds

    def get_internal_block_refs(self) -> List:
        return [r for r, _ in self._execute()]

    # ----------------------------------------------------------- consumption
    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 1,
                     drop_last: bool = False):
        from ray_tpu.data.iterator import iter_batches as _ib
        return _ib(self._execute(), batch_size=batch_size,
                   batch_format=batch_format, drop_last=drop_last)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref, _ in self._execute():
            yield from block_lib.block_to_rows(ray_tpu.get(ref))

    def iter_jax_batches(self, *, batch_size: int, mesh=None, sharding=None,
                         batch_format: str = "numpy", drop_last: bool = True,
                         prefetch: int = 2, device_prefetch: int = 2,
                         dtypes=None):
        from ray_tpu.data.iterator import iter_jax_batches as _ijb
        return _ijb(self._execute(), batch_size=batch_size, mesh=mesh,
                    sharding=sharding, drop_last=drop_last,
                    prefetch=prefetch, device_prefetch=device_prefetch,
                    dtypes=dtypes)

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(meta.num_rows for _, meta in self._execute())

    def schema(self):
        for ref, meta in self._execute():
            if meta.schema is not None:
                return meta.schema
        return None

    def num_blocks(self) -> int:
        return len(list(self._execute()))

    def to_pandas(self):
        blocks = [ray_tpu.get(r) for r, _ in self._execute()]
        return block_lib.concat_blocks(blocks).to_pandas()

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None):
        """One executing stream, n disjoint consumers (reference:
        Dataset.streaming_split -> OutputSplitter)."""
        from ray_tpu.data.split import streaming_split
        return streaming_split(self, n, equal=equal,
                               locality_hints=locality_hints)

    def split(self, n: int) -> List["Dataset"]:
        bundles = list(self._execute())
        shards: List[List[exe.RefBundle]] = [[] for _ in range(n)]
        # greedy row balancing
        order = sorted(bundles, key=lambda b: -b[1].num_rows)
        sizes = [0] * n
        for b in order:
            i = sizes.index(min(sizes))
            shards[i].append(b)
            sizes[i] += b[1].num_rows
        return [Dataset([exe.InputStage(s)]) for s in shards]

    # ---------------------------------------------------------------- writes
    # Distributed: each block is written by a REMOTE task on whatever
    # node holds it (reference: ray.data write_* fan out write tasks;
    # the driver never materializes block bytes), and paths go through
    # the URI storage plane so gs://bucket/out works like a local dir.
    def _write_blocks(self, path: str, fmt: str):
        from ray_tpu.util import storage
        storage.makedirs(path)

        def _write_one(block, dst):
            import io as _io
            import json as _json

            from ray_tpu.data import block as B
            from ray_tpu.util import storage as _storage
            buf = _io.BytesIO()
            if fmt == "parquet":
                import pyarrow.parquet as pq
                pq.write_table(block, buf)
            elif fmt == "csv":
                import pyarrow.csv as pcsv
                pcsv.write_csv(block, buf)
            else:
                for row in B.block_to_rows(block):
                    buf.write((_json.dumps(row, default=str) + "\n")
                              .encode())
            _storage.write_bytes(dst, buf.getvalue())
            return True

        ext = {"parquet": "parquet", "csv": "csv", "json": "json"}[fmt]
        task = ray_tpu.remote(_write_one)
        from ray_tpu.util import storage as _s
        refs = [task.remote(ref, _s.join(path, f"part-{i:05d}.{ext}"))
                for i, (ref, _) in enumerate(self._execute())]
        ray_tpu.get(refs)

    def write_parquet(self, path: str):
        self._write_blocks(path, "parquet")

    def write_csv(self, path: str):
        self._write_blocks(path, "csv")

    def write_json(self, path: str):
        self._write_blocks(path, "json")

    def __repr__(self):
        return f"Dataset(stages={len(self._stages)})"


class GroupedData:
    """Grouped view for aggregations (reference: ray.data
    grouped_data.GroupedData — count/sum/mean/min/max/std + map_groups
    over a distributed key-hash shuffle)."""

    _ARROW_FNS = {"sum": "sum", "mean": "mean", "min": "min",
                  "max": "max", "count": "count", "std": "stddev"}

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, col: str, fn: str, out_name: str) -> Dataset:
        from ray_tpu.data import shuffle as shuffle_lib
        return self._ds._extend(shuffle_lib.ShuffleStage(
            "groupby_agg", key=self._key,
            aggs=[(col, self._ARROW_FNS[fn], out_name)]))

    def count(self) -> Dataset:
        return self._agg(self._key, "count", "count()")

    def sum(self, col: str) -> Dataset:
        return self._agg(col, "sum", f"sum({col})")

    def mean(self, col: str) -> Dataset:
        return self._agg(col, "mean", f"mean({col})")

    def min(self, col: str) -> Dataset:
        return self._agg(col, "min", f"min({col})")

    def max(self, col: str) -> Dataset:
        return self._agg(col, "max", f"max({col})")

    def std(self, col: str) -> Dataset:
        return self._agg(col, "std", f"std({col})")

    def map_groups(self, fn) -> Dataset:
        from ray_tpu.data import shuffle as shuffle_lib
        return self._ds._extend(shuffle_lib.ShuffleStage(
            "map_groups", key=self._key, fn=fn))
