"""Streaming execution of dataset plans.

Re-design of the reference's StreamingExecutor (reference:
python/ray/data/_internal/execution/streaming_executor.py:48 — dedicated
thread, operator scheduling loop, backpressure policies). Here each
operator is a generator stage over a stream of block refs: map stages keep
a bounded window of in-flight remote tasks (pipelining + backpressure in
~40 lines instead of a scheduling loop). All-to-all reshapes run as the
push-based streaming shuffle in ray_tpu/data/shuffle.py (map tasks
partition each block as it arrives, reduce tasks stream-merge with
locality placement and spill-backed overflow); the materializing
AllToAllStage below survives only as the tiny-input fallback. Only refs
flow through the executor; blocks stay in the object store."""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data import block as block_lib

# (ref, BlockMetadata) pairs flow between stages
RefBundle = Tuple[Any, block_lib.BlockMetadata]

DEFAULT_MAX_IN_FLIGHT = 8


class ExecutionBudget:
    """Cross-operator resource budget (reference: execution/
    resource_manager.py + backpressure_policy/ — the streaming executor
    throttles operators against cluster resources instead of letting one
    stage flood the object store). One budget is shared by every stage of
    a plan: a stage may only widen its in-flight window while under both
    the task cap and the bytes cap; at the cap it drains its own window
    head first (pull-based stages always keep making progress, so this
    throttles without deadlock)."""

    def __init__(self, max_tasks: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        if max_tasks is None or max_bytes is None:
            d_tasks, d_bytes = self._cluster_defaults()
            max_tasks = max_tasks if max_tasks is not None else d_tasks
            max_bytes = max_bytes if max_bytes is not None else d_bytes
        self.max_tasks = max_tasks
        self.max_bytes = max_bytes
        self.tasks = 0
        self.bytes = 0

    @staticmethod
    def _cluster_defaults():
        """Scale the budget to the CLUSTER, not a constant: in-flight
        tasks track total CPUs (x2 for pipelining) and in-flight bytes
        track a quarter of aggregate object-store capacity (reference:
        execution/resource_manager.py derives caps from cluster resources
        the same way). Falls back to single-node-ish constants when no
        cluster is attached."""
        try:
            import ray_tpu
            if ray_tpu.is_initialized():
                total = ray_tpu.cluster_resources()
                cpus = int(total.get("CPU", 8))
                store = float(total.get("object_store_memory",
                                        1024 * 1024 * 1024))
                return (max(8, 2 * cpus),
                        max(64 * 1024 * 1024, int(store // 4)))
        except Exception:
            pass
        return 32, 256 * 1024 * 1024

    def try_acquire(self, est_bytes: int, force: bool = False) -> bool:
        """force=True always succeeds (still counted): a stage with an
        EMPTY window must launch regardless of the budget, otherwise an
        upstream stage whose tokens are all held downstream (or vice
        versa) livelocks the pipeline. Total in-flight stays bounded by
        max_tasks + n_stages."""
        if not force:
            if self.tasks + 1 > self.max_tasks:
                return False
            if self.bytes + est_bytes > self.max_bytes and self.tasks > 0:
                return False
        self.tasks += 1
        self.bytes += est_bytes
        return True

    def release(self, est_bytes: int) -> None:
        self.tasks -= 1
        self.bytes -= est_bytes


def _apply_one(fn_kind: str, fn, block, batch_format: str,
               fn_args, fn_kwargs):
    from ray_tpu.data import block as B
    if fn_kind == "map_batches":
        batch = B.block_to_batch(block, batch_format)
        out = fn(batch, *fn_args, **(fn_kwargs or {}))
        if hasattr(out, "__next__"):
            raise TypeError(
                "map_batches UDF returned a generator from a "
                "non-generator callable; declare it as a generator "
                "FUNCTION (def f(batch): yield ...) so the stage streams "
                "its chunks — wrapping one in a lambda hides it from "
                "streaming detection")
        return B.block_from_batch(out)
    if fn_kind == "map":
        return B.block_from_rows(
            [fn(r, *fn_args, **(fn_kwargs or {}))
             for r in B.block_to_rows(block)])
    if fn_kind == "filter":
        return B.block_from_rows(
            [r for r in B.block_to_rows(block)
             if fn(r, *fn_args, **(fn_kwargs or {}))])
    if fn_kind == "flat_map":
        rows = []
        for r in B.block_to_rows(block):
            rows.extend(fn(r, *fn_args, **(fn_kwargs or {})))
        return B.block_from_rows(rows)
    if fn_kind == "select_columns":
        # arrow-native projection: no row/pandas materialization
        # (fn carries the column list — ProjectStage)
        return block.select(fn_args[0])
    raise ValueError(fn_kind)


def _map_block_remote(ops, block):
    """Runs inside a worker: apply a CHAIN of transforms to one block —
    a fused .map().filter().map_batches() pipeline touches the object
    store once, not once per operator (reference: operator fusion rule,
    _internal/logical/rules/operator_fusion.py). Returns (block,
    metadata); the block stays in the executing node's store and the
    driver only reads the metadata."""
    from ray_tpu.data import block as B
    for (fn_kind, fn, batch_format, fn_args, fn_kwargs) in ops:
        block = _apply_one(fn_kind, fn, block, batch_format,
                           fn_args, fn_kwargs)
    return block, B.block_metadata(block)


def _iter_chain_blocks(ops, block, i=0):
    """Apply ops[i:] to one block, yielding OUTPUT blocks: a map_batches
    UDF that returns a generator fans one input block out into many
    output blocks, each flowing through the remaining fused ops
    independently (reference: generator-UDF map tasks stream blocks via
    streaming generators instead of buffering the whole expansion,
    _internal/execution/operators/map_transformer.py)."""
    from ray_tpu.data import block as B
    if i == len(ops):
        yield block
        return
    fn_kind, fn, batch_format, fn_args, fn_kwargs = ops[i]
    if fn_kind == "map_batches":
        batch = B.block_to_batch(block, batch_format)
        out = fn(batch, *fn_args, **(fn_kwargs or {}))
        if hasattr(out, "__next__"):    # generator UDF: stream chunks
            for chunk in out:
                yield from _iter_chain_blocks(
                    ops, B.block_from_batch(chunk), i + 1)
            return
        yield from _iter_chain_blocks(ops, B.block_from_batch(out), i + 1)
        return
    yield from _iter_chain_blocks(
        ops, _apply_one(fn_kind, fn, block, batch_format,
                        fn_args, fn_kwargs), i + 1)


def _map_block_stream_remote(ops, block):
    """Streaming-generator map task: yields (block, metadata) as
    alternating items so the driver can read the small metadata without
    ever pulling block bytes (block item stays in the executor node's
    store; the consumer holds only its ref)."""
    from ray_tpu.data import block as B
    for out in _iter_chain_blocks(ops, block):
        yield out
        yield B.block_metadata(out)


def _read_blocks_stream(fn):
    """Streaming read task: a datasource fn marked yields_blocks
    produces blocks incrementally (e.g. one parquet row group at a
    time); backpressure keeps at most K unconsumed blocks alive instead
    of buffering the whole file."""
    from ray_tpu.data import block as B
    for blk in fn():
        yield blk
        yield B.block_metadata(blk)


def _drain_pair_stream(gen):
    """Consume a (block, meta, block, meta, ...) item stream into
    (block_ref, meta) bundles, fetching only the metadata items. A
    mid-stream task error arrives as a lone trailing item: resolving it
    re-raises the executor's exception."""
    while True:
        try:
            block_ref = next(gen)
        except StopIteration:
            return
        try:
            meta_ref = next(gen)
        except StopIteration:
            ray_tpu.get(block_ref)   # lone item == the error; raises
            return
        yield (block_ref, ray_tpu.get(meta_ref))


class Stage:
    """Base: transforms a stream of RefBundles."""

    def execute(self, upstream: Iterator[RefBundle],
                budget: Optional[ExecutionBudget] = None
                ) -> Iterator[RefBundle]:
        raise NotImplementedError


class InputStage(Stage):
    def __init__(self, bundles: List[RefBundle]):
        self.bundles = bundles

    def execute(self, upstream, budget=None):
        yield from self.bundles


class ReadStage(Stage):
    """Launches read tasks from serialized read descriptors."""

    name = "Read"

    def __init__(self, read_fns: List[Callable], max_in_flight: int = None,
                 concurrency: Optional[int] = None):
        self.read_fns = read_fns
        self.max_in_flight = (concurrency or max_in_flight
                              or DEFAULT_MAX_IN_FLIGHT)

    EST_READ_BYTES = 8 * 1024 * 1024    # pre-read output size guess

    def execute(self, upstream, budget=None):
        # two returns: the block ref is yielded WITHOUT fetching its bytes
        # to the driver; only the small metadata ref is materialized.
        # Datasource fns marked yields_blocks run as streaming-generator
        # tasks instead: one task emits many blocks with bounded
        # buffering (reference: streaming reads over file fragments)
        remote_read = ray_tpu.remote(num_returns=2)(
            lambda fn: _with_meta(fn()))
        remote_read_stream = ray_tpu.remote(
            num_returns="streaming")(_read_blocks_stream)
        window = collections.deque()
        fns = iter(self.read_fns)
        exhausted = False
        while True:
            while not exhausted and len(window) < self.max_in_flight:
                if budget is not None and not budget.try_acquire(
                        self.EST_READ_BYTES, force=not window):
                    break
                fn = next(fns, None)
                if fn is None:
                    if budget is not None:
                        budget.release(self.EST_READ_BYTES)
                    exhausted = True
                    break
                if getattr(fn, "yields_blocks", False):
                    window.append(("stream",
                                   remote_read_stream.remote(fn)))
                else:
                    window.append(("task", remote_read.remote(fn)))
            if not window:
                return
            kind, handle = window.popleft()
            if budget is not None:
                budget.release(self.EST_READ_BYTES)
            if kind == "stream":
                yield from _drain_pair_stream(handle)
            else:
                block_ref, meta_ref = handle
                yield (block_ref, ray_tpu.get(meta_ref))


def _with_meta(block):
    return block, block_lib.block_metadata(block)


class MapStage(Stage):
    """One (or a fused chain of) map-family transform(s); each input
    block becomes one remote task applying every fused op in sequence."""

    def __init__(self, fn_kind: str, fn, batch_format: str = "numpy",
                 fn_args=(), fn_kwargs=None, max_in_flight: int = None,
                 concurrency: Optional[int] = None,
                 num_cpus: Optional[float] = None):
        import inspect
        self.ops = [(fn_kind, fn, batch_format, fn_args, fn_kwargs)]
        self.concurrency = concurrency
        self.num_cpus = num_cpus
        self.max_in_flight = (concurrency or max_in_flight
                              or DEFAULT_MAX_IN_FLIGHT)
        # generator UDF (yields output batches): run the block task as a
        # streaming generator so chunks flow out with bounded buffering
        self.streaming = (fn_kind == "map_batches"
                          and inspect.isgeneratorfunction(fn))

    @property
    def name(self) -> str:
        return "Map(" + "->".join(k for k, *_ in self.ops) + ")"

    @staticmethod
    def fused(a: "MapStage", b: "MapStage") -> "MapStage":
        """a then b as ONE task per block (task-pool stages only; the
        optimizer never fuses across ActorPoolMapStage/AllToAll)."""
        out = MapStage.__new__(MapStage)
        out.ops = a.ops + b.ops
        out.concurrency = (min(a.concurrency, b.concurrency)
                           if a.concurrency and b.concurrency
                           else a.concurrency or b.concurrency)
        out.num_cpus = (max(a.num_cpus, b.num_cpus)
                        if a.num_cpus and b.num_cpus
                        else a.num_cpus or b.num_cpus)
        out.max_in_flight = min(a.max_in_flight, b.max_in_flight)
        out.streaming = a.streaming or b.streaming
        return out

    def execute(self, upstream, budget=None):
        opts = {"num_returns": 2}
        if self.num_cpus is not None:
            opts["num_cpus"] = self.num_cpus
        if self.streaming:
            s_opts = dict(opts, num_returns="streaming")
            remote_map = ray_tpu.remote(**s_opts)(_map_block_stream_remote)
        else:
            remote_map = ray_tpu.remote(**opts)(_map_block_remote)
        window = collections.deque()
        upstream = iter(upstream)
        exhausted = False
        # rolling output-size estimate for the byte budget: last input
        # block's size (metadata-driven, like op_runtime_metrics);
        # per-execution local so concurrent runs don't share state
        peek_est = 0
        while True:
            while not exhausted and len(window) < self.max_in_flight:
                est = 0
                if budget is not None:
                    est = peek_est
                    if not budget.try_acquire(est, force=not window):
                        break
                nxt = next(upstream, None)
                if nxt is None:
                    if budget is not None:
                        budget.release(est)
                    exhausted = True
                    break
                ref, meta = nxt
                peek_est = getattr(meta, "size_bytes", 0) or 0
                window.append((remote_map.remote(self.ops, ref), est))
            if not window:
                return
            handle, est = window.popleft()
            if budget is not None:
                budget.release(est)
            if self.streaming:
                # one input block -> a stream of output bundles
                yield from _drain_pair_stream(handle)
            else:
                block_ref, meta_ref = handle
                # block until this output's metadata is ready (keeps
                # order; later tasks keep running in the window); bytes
                # stay put
                yield (block_ref, ray_tpu.get(meta_ref))


class ProjectStage(MapStage):
    """Column projection (`select_columns`) as a first-class stage so
    the optimizer can SEE it: _pushdown_projection rebinds
    column-prunable read fns (parquet) to fetch only these columns
    (reference: logical/rules — projection pushdown into the
    datasource). The stage itself still runs as an ordinary fused map:
    it is the exact cut when the source can't prune."""

    def __init__(self, columns):
        self.columns = list(columns)
        super().__init__("select_columns", None, fn_args=(self.columns,))


def _pushdown_projection(stages: List[Stage]) -> List[Stage]:
    """Rebind a ReadStage's column-prunable read fns when a
    ProjectStage follows it with only limits in between — the read then
    never materializes the dropped columns. Sound only for that shape:
    an arbitrary UDF between read and project may consume columns the
    projection drops. Only the FIRST projection of a chain pushes down
    (it names the widest set that chain may reference; narrower chained
    selects still prune their subset downstream — pushing a later,
    narrower one would starve the earlier select of its columns)."""
    out = list(stages)
    for i, s in enumerate(out):
        if not isinstance(s, ReadStage):
            continue
        if not all(hasattr(fn, "with_columns") for fn in s.read_fns):
            continue
        j = i + 1
        while j < len(out) and isinstance(out[j], LimitStage):
            j += 1
        if j < len(out) and isinstance(out[j], ProjectStage):
            out[i] = ReadStage([fn.with_columns(out[j].columns)
                                for fn in s.read_fns],
                               max_in_flight=s.max_in_flight)
    return out


class ActorPoolMapStage(Stage):
    """Stateful transforms on a pool of long-lived actors (reference:
    ActorPoolMapOperator, _internal/execution/operators/ — used when the
    UDF is a callable class whose construction is expensive: model
    weights, tokenizers, device state). Blocks round-robin onto the
    least-loaded actor with a bounded per-actor pipeline."""

    def __init__(self, fn_cls, batch_format: str = "numpy",
                 fn_constructor_args=(), fn_constructor_kwargs=None,
                 fn_args=(), fn_kwargs=None, pool_size: int = 2,
                 max_in_flight_per_actor: int = 2,
                 num_cpus: float = 0.5):
        self.fn_cls = fn_cls
        self.batch_format = batch_format
        self.ctor_args = fn_constructor_args
        self.ctor_kwargs = fn_constructor_kwargs or {}
        self.fn_args = fn_args
        self.fn_kwargs = fn_kwargs or {}
        self.pool_size = pool_size
        self.per_actor = max_in_flight_per_actor
        self.num_cpus = num_cpus

    def execute(self, upstream, budget=None):
        fn_cls = self.fn_cls
        batch_format = self.batch_format
        fn_args, fn_kwargs = self.fn_args, self.fn_kwargs

        @ray_tpu.remote(num_cpus=self.num_cpus, max_concurrency=1)
        class _MapWorker:
            def __init__(self, ctor_args, ctor_kwargs):
                self._fn = fn_cls(*ctor_args, **ctor_kwargs)

            def apply(self, block):
                from ray_tpu.data import block as B
                batch = B.block_to_batch(block, batch_format)
                out = self._fn(batch, *fn_args, **fn_kwargs)
                out_block = B.block_from_batch(out)
                return out_block, B.block_metadata(out_block)

        actors = [_MapWorker.remote(self.ctor_args, self.ctor_kwargs)
                  for _ in range(self.pool_size)]
        load = {i: 0 for i in range(self.pool_size)}
        window = collections.deque()   # (result_ref, actor_idx)
        upstream = iter(upstream)
        exhausted = False
        peek_est = 0   # rolling output estimate = last input block size
        try:
            while True:
                while (not exhausted
                       and len(window) < self.pool_size * self.per_actor):
                    est = 0
                    if budget is not None:
                        est = peek_est
                        if not budget.try_acquire(est, force=not window):
                            break
                    nxt = next(upstream, None)
                    if nxt is None:
                        if budget is not None:
                            budget.release(est)
                        exhausted = True
                        break
                    ref, meta = nxt
                    peek_est = getattr(meta, "size_bytes", 0) or 0
                    idx = min(load, key=load.get)
                    load[idx] += 1
                    window.append(
                        (actors[idx].apply.options(num_returns=2)
                         .remote(ref), idx, est))
                if not window:
                    return
                (block_ref, meta_ref), idx, est = window.popleft()
                load[idx] -= 1
                if budget is not None:
                    budget.release(est)
                yield (block_ref, ray_tpu.get(meta_ref))
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass


class AllToAllStage(Stage):
    """LEGACY materializing reshape (repartition / shuffle / sort):
    buffers every input ref at a barrier before reshaping. Kept only as
    the tiny-input fallback of ray_tpu.data.shuffle.ShuffleStage — at
    <= a couple of blocks the barrier is free and the single-block local
    paths below are exact; everything larger streams."""

    def __init__(self, kind: str, **kwargs):
        self.kind = kind
        self.kwargs = kwargs

    @property
    def name(self) -> str:
        return f"AllToAll({self.kind})"

    def execute(self, upstream, budget=None):
        bundles = list(upstream)
        refs = [r for r, _ in bundles]
        if self.kind == "repartition":
            yield from self._repartition(refs, self.kwargs["num_blocks"])
        elif self.kind == "random_shuffle":
            yield from self._random_shuffle(refs, self.kwargs.get("seed"))
        elif self.kind == "sort":
            yield from self._sort(refs, self.kwargs["key"],
                                  self.kwargs.get("descending", False))
        elif self.kind == "groupby_agg":
            yield from self._groupby_agg(refs, self.kwargs["key"],
                                         self.kwargs["aggs"])
        elif self.kind == "map_groups":
            yield from self._map_groups(refs, self.kwargs["key"],
                                        self.kwargs["fn"])
        else:
            raise ValueError(self.kind)

    def _repartition(self, refs, num_blocks: int):
        blocks = ray_tpu.get(list(refs))
        merged = block_lib.concat_blocks(blocks)
        n = max(1, num_blocks)
        rows = merged.num_rows
        per = (rows + n - 1) // n if rows else 0
        for i in range(n):
            part = block_lib.slice_block(merged, min(i * per, rows),
                                         min((i + 1) * per, rows)) \
                if rows else merged
            yield (ray_tpu.put(part), block_lib.block_metadata(part))

    def _random_shuffle(self, refs, seed):
        """Distributed map/reduce shuffle: blocks never materialize in the
        driver (reference: _internal/planner/exchange ShuffleTaskSpec);
        single-block datasets take the local path."""
        import numpy as np
        if len(refs) <= 1:
            blocks = ray_tpu.get(list(refs))
            merged = block_lib.concat_blocks(blocks)
            rng = np.random.default_rng(seed)
            shuffled = merged.take(rng.permutation(merged.num_rows))
            yield (ray_tpu.put(shuffled),
                   block_lib.block_metadata(shuffled))
            return
        from ray_tpu.data import exchange
        n = len(refs)
        seeds = np.random.default_rng(seed).integers(0, 2**31, size=n + 1)
        yield from exchange.exchange(
            list(refs), n, exchange.partition_random, (n, int(seeds[0])),
            exchange.reduce_concat, (int(seeds[1]),))

    def _sort(self, refs, key, descending):
        """Distributed range-partitioned sort (reference: SortTaskSpec —
        sample boundaries, partition by range, merge-sort per partition);
        output partitions are globally ordered."""
        if len(refs) <= 1:
            blocks = ray_tpu.get(list(refs))
            merged = block_lib.concat_blocks(blocks)
            order = "descending" if descending else "ascending"
            out = merged.sort_by([(key, order)])
            yield (ray_tpu.put(out), block_lib.block_metadata(out))
            return
        from ray_tpu.data import exchange
        n = len(refs)
        bounds = exchange.sample_sort_bounds(list(refs), key, n)
        yield from exchange.exchange(
            list(refs), len(bounds) + 1, exchange.partition_range,
            (key, bounds, descending), exchange.reduce_sorted,
            (key, descending))

    def _groupby_agg(self, refs, key, aggs):
        """aggs: list of (column, arrow_agg_fn, out_name); hash-exchange
        to key-disjoint partitions, each aggregated in its reduce task
        (reference: hash-shuffle groupby under
        _internal/planner/exchange)."""
        from ray_tpu.data import exchange
        n = max(1, min(len(refs), 8))
        yield from exchange.exchange(
            list(refs), n, exchange.partition_hash, (key, n),
            exchange.reduce_agg, (key, list(aggs)))

    def _map_groups(self, refs, key, fn):
        """Run fn(pandas.DataFrame) per key group (reference:
        GroupedData.map_groups) via the hash exchange."""
        from ray_tpu.data import exchange
        n = max(1, min(len(refs), 8))
        yield from exchange.exchange(
            list(refs), n, exchange.partition_hash, (key, n),
            exchange.reduce_map_groups, (key, fn))


class LimitStage(Stage):
    def __init__(self, limit: int):
        self.limit = limit

    def execute(self, upstream, budget=None):
        remaining = self.limit
        for ref, meta in upstream:
            if remaining <= 0:
                return
            if meta.num_rows <= remaining:
                remaining -= meta.num_rows
                yield (ref, meta)
            else:
                block = ray_tpu.get(ref)
                part = block_lib.slice_block(block, 0, remaining)
                remaining = 0
                yield (ray_tpu.put(part), block_lib.block_metadata(part))
                return


class StageStats:
    """Per-operator runtime metrics (reference: _internal/stats.py +
    op_runtime_metrics.py — rows/bytes/tasks/wall per operator,
    surfaced as Dataset.stats())."""

    def __init__(self, name: str):
        self.name = name
        self.tasks = 0        # output bundles == tasks for read/map stages
        self.rows = 0
        self.bytes = 0
        self.wall_s = 0.0
        self.done = False

    def line(self, self_wall_s: Optional[float] = None) -> str:
        mb = self.bytes / (1024 * 1024)
        wall = self.wall_s if self_wall_s is None else self_wall_s
        return (f"{self.name}: {self.tasks} tasks, {self.rows} rows, "
                f"{mb:.2f} MiB, {wall * 1e3:.0f} ms")


class ExecutionStats:
    def __init__(self):
        self.stages: List[StageStats] = []
        self.total_wall_s = 0.0

    def summary(self) -> str:
        # a stage's measured wall INCLUDES its whole upstream chain
        # (pull-based generators); report the nested-profiler difference
        # so each operator shows only its own contribution
        lines = []
        prev = 0.0
        for i, st in enumerate(self.stages):
            lines.append(
                f"Operator {i} {st.line(max(0.0, st.wall_s - prev))}")
            prev = max(prev, st.wall_s)
        lines.append(f"Total: {self.total_wall_s * 1e3:.0f} ms")
        return "\n".join(lines)


def _instrument(stream: Iterator[RefBundle], st: StageStats
                ) -> Iterator[RefBundle]:
    import time

    from ray_tpu._private import events
    span = None
    try:
        while True:
            t0 = time.perf_counter()
            if span is None:
                # opened on FIRST pull (plans build lazily; a stage the
                # consumer never reaches must not appear on the timeline)
                span = events.start_span("data.stage", category="data",
                                         stage=st.name)
            try:
                ref, meta = next(stream)
            except StopIteration:
                st.wall_s += time.perf_counter() - t0
                st.done = True
                return
            st.wall_s += time.perf_counter() - t0
            st.tasks += 1
            st.rows += getattr(meta, "num_rows", 0) or 0
            st.bytes += getattr(meta, "size_bytes", 0) or 0
            yield (ref, meta)
    finally:
        # runs on exhaustion AND on early termination (limit pushdown,
        # consumer walked away): a truncated stage still records, marked
        if span is not None:
            span.end(tasks=st.tasks, rows=st.rows, bytes=st.bytes,
                     wall_ms=round(st.wall_s * 1e3, 3),
                     truncated=not st.done)


def _pushdown_limits(stages: List[Stage]) -> List[Stage]:
    """Move a LimitStage ahead of row-count-preserving map stages so
    upstream work stops as soon as `n` rows exist (reference:
    logical/rules/limit_pushdown.py). Only `map` preserves cardinality
    1:1 (filter/flat_map/map_batches may change it), and the original
    limit stays in place as the exact cut."""
    out = list(stages)
    i = 1
    while i < len(out):
        s = out[i]
        if isinstance(s, LimitStage):
            j = i
            while j > 0 and isinstance(out[j - 1], MapStage) \
                    and all(k in ("map", "select_columns")
                            for k, *_ in out[j - 1].ops):
                j -= 1
            if j < i:
                out.insert(j, LimitStage(s.limit))
                i += 1    # the insertion shifted everything right
        i += 1
    return out


def optimize_plan(stages: List[Stage]) -> List[Stage]:
    """Rule passes (reference: _internal/logical/rules/):
    1. limit pushdown past row-preserving maps
    2. fuse adjacent task-pool map-family stages so a .map().filter()
       chain pays ONE object-store round trip per block
       (operator_fusion.py). Actor-pool/all-to-all stages are barriers."""
    stages = _pushdown_projection(stages)
    stages = _pushdown_limits(stages)
    out: List[Stage] = []
    for s in stages:
        if (out and isinstance(s, MapStage) and isinstance(out[-1],
                                                           MapStage)):
            out[-1] = MapStage.fused(out[-1], s)
        else:
            out.append(s)
    return out


def execute_plan(stages: List[Stage],
                 budget: Optional[ExecutionBudget] = None,
                 stats: Optional[ExecutionStats] = None,
                 optimize: bool = True) -> Iterator[RefBundle]:
    budget = budget or ExecutionBudget()
    if optimize:
        stages = optimize_plan(stages)
    stream: Iterator[RefBundle] = iter(())
    for stage in stages:
        stream = stage.execute(stream, budget)
        if stats is not None:
            st = StageStats(getattr(stage, "name", None)
                            or type(stage).__name__)
            stats.stages.append(st)
            stream = _instrument(stream, st)
    if stats is not None:
        stream = _total_wall(stream, stats)
    return stream


def _total_wall(stream: Iterator[RefBundle], stats: ExecutionStats
                ) -> Iterator[RefBundle]:
    import time
    t0 = time.perf_counter()
    try:
        yield from stream
    finally:
        stats.total_wall_s = time.perf_counter() - t0
