"""Push-based streaming map/reduce shuffle (reference: Exoshuffle /
python/ray/data/_internal/planner/exchange — pipelined shuffle inside the
streaming executor instead of an all-to-all barrier).

Shape: the stage consumes its upstream stream INCREMENTALLY. Each input
block runs a map task that partitions it into P sub-blocks and seals them
into the object store (riding the off-loop parallel put path — task
returns serialize and copy on the executing worker, never the driver).
Sub-blocks are pushed into per-partition runs as their map task finishes;
once a partition accumulates a fixed-size contiguous run it is folded by
an intermediate MERGE task (concat on the node holding the run's bytes),
so the driver's live-ref footprint per partition stays bounded. When the
input is exhausted, one REDUCE task per partition stream-merges its runs
(permute / sort / aggregate) with soft locality placement on the node
holding the plurality of the partition's bytes — the same
object_locations plane streaming_split's locality dealing uses.

Memory bound: the driver holds at most ``max_in_flight`` input-block refs
at any time (peak tracked in ShuffleStats.peak_live_inputs and asserted
in tests); physical sub-block bytes beyond the object-store budget spill
to disk via the node manager's spill loop and restore on reduce, so a
shuffle larger than the store completes instead of OOMing.

Determinism: merge runs are fixed-size contiguous map-index ranges (the
grouping can never depend on task completion timing) and every random
seed is derived from (user seed, phase, index), so ``random_shuffle``
with a seed is a reproducible permutation.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data import block as block_lib
from ray_tpu.data import exchange

DEFAULT_MERGE_FACTOR = 8        # sub-blocks folded per intermediate merge
DEFAULT_MAX_MAPS = 8            # in-flight map tasks == live input refs
DEFAULT_MAX_MERGES = 8          # in-flight merge tasks before the driver waits
DEFAULT_MAX_REDUCES = 8         # in-flight reduce tasks
SMALL_INPUT_BLOCKS = 2          # <= this many inputs -> legacy materializing path


class ShuffleStats:
    """Observability for one shuffle execution (the peak-live gauges are
    the memory-bound evidence the acceptance test asserts)."""

    def __init__(self, kind: str):
        self.kind = kind
        self.num_partitions = 0
        self.map_tasks = 0
        self.merge_tasks = 0
        self.reduce_tasks = 0
        self.input_blocks = 0
        self.input_bytes = 0
        self.output_rows = 0
        self.output_bytes = 0
        self.live_inputs = 0          # current in-flight map tasks
        self.peak_live_inputs = 0     # max input-block refs held at once
        self.live_partials = 0        # current unmerged sub-block refs
        self.peak_live_partials = 0
        self.locality_hits = 0        # reduces placed on a data-holding node
        self.fallback = False         # took the legacy materializing path

    def _touch_inputs(self, delta: int):
        self.live_inputs += delta
        self.peak_live_inputs = max(self.peak_live_inputs, self.live_inputs)

    def _touch_partials(self, delta: int):
        self.live_partials += delta
        self.peak_live_partials = max(self.peak_live_partials,
                                      self.live_partials)

    def summary(self) -> str:
        return (f"Shuffle({self.kind}): {self.input_blocks} blocks -> "
                f"{self.num_partitions} partitions, "
                f"{self.map_tasks}/{self.merge_tasks}/{self.reduce_tasks} "
                f"map/merge/reduce tasks, peak live inputs "
                f"{self.peak_live_inputs}, peak live partials "
                f"{self.peak_live_partials}")


_LAST_STATS: Optional[ShuffleStats] = None


def last_shuffle_stats() -> Optional[ShuffleStats]:
    """Stats of the most recently COMPLETED shuffle in this process."""
    return _LAST_STATS


def object_node_ids(refs) -> List[Optional[str]]:
    """Best-effort node id per ref from the owner's location table (the
    cheap path streaming_split's locality dealing uses; None = unknown)."""
    refs = list(refs)
    try:
        from ray_tpu._private.worker import global_worker
        return global_worker.core.object_locations(refs)
    except Exception:
        return [None] * len(refs)


def plurality_node(refs_and_bytes) -> Optional[str]:
    """Node holding the plurality of the given (ref, nbytes) pairs."""
    pairs = list(refs_and_bytes)
    if not pairs:
        return None
    locs = object_node_ids(r for r, _ in pairs)
    weight: Dict[str, int] = {}
    for loc, (_, nb) in zip(locs, pairs):
        if loc is not None:
            weight[loc] = weight.get(loc, 0) + max(1, int(nb or 0))
    if not weight:
        return None
    return max(weight, key=weight.get)


def default_num_partitions(cap: int = 16) -> int:
    """Cluster-scaled partition count: ~2 tasks per CPU, clamped."""
    try:
        if ray_tpu.is_initialized():
            cpus = int(ray_tpu.cluster_resources().get("CPU", 4))
            return max(2, min(cap, 2 * cpus))
    except Exception:
        pass
    return max(2, min(cap, 8))


# ----------------------------------------------------------- remote bodies
def _shuffle_map(block, partition_fn, args, n):
    """Partition one input block into n sub-blocks; returns the
    sub-blocks plus one (rows, bytes) list so the driver accounts sizes
    without ever fetching block bytes. num_returns == n + 1."""
    parts = list(partition_fn(block, *args))
    sizes = [(p.num_rows, p.nbytes) for p in parts]
    return (*parts, sizes)


def _shuffle_merge(*parts):
    """Fold a contiguous run of sub-blocks into one block (order
    preserving — determinism of the final concat relies on it)."""
    out = block_lib.concat_blocks(list(parts))
    return out, (out.num_rows, out.nbytes)


def _shuffle_reduce(reduce_fn, reduce_args, *parts):
    out = reduce_fn(*reduce_args, *parts)
    return out, block_lib.block_metadata(out)


def _derived_seed(seed, phase: int, index: int):
    """Deterministic per-task seed material; None stays None (fresh
    entropy per task, matching numpy's default_rng(None) contract)."""
    if seed is None:
        return None
    return [int(seed) & 0x7FFFFFFF, phase, index]


class _Partition:
    """Driver-side state of one reduce partition. Sub-blocks are keyed
    by their map index; merged runs cover FIXED index ranges
    [m*F, (m+1)*F), so both the fold grouping and the final assembly
    order depend only on indices — never on task completion timing."""

    __slots__ = ("arrived", "runs", "bytes", "rows")

    def __init__(self):
        self.arrived: Dict[int, Tuple[Any, int, int]] = {}  # idx -> (ref, rows, nb)
        self.runs: Dict[int, Tuple[Any, int, int]] = {}     # run m -> merged
        self.bytes = 0
        self.rows = 0

    def reduce_refs(self, merge_factor: int) -> List:
        """All refs in deterministic global map-index order (a merged
        run sorts at its range start; leftovers at their own index)."""
        items = [(m * merge_factor, r) for m, (r, _, _) in self.runs.items()]
        items += [(i, v[0]) for i, v in self.arrived.items()]
        return [r for _, r in sorted(items, key=lambda kv: kv[0])]

    def locality_pairs(self):
        return ([(r, nb) for r, _, nb in self.runs.values()]
                + [(v[0], v[2]) for v in self.arrived.values()])


class ShuffleStage:
    """Streaming all-to-all stage. Drop-in replacement for the
    materializing AllToAllStage: same kinds, same kwargs, but the input
    stream is consumed incrementally with bounded live refs. Tiny inputs
    (<= SMALL_INPUT_BLOCKS blocks) fall back to the legacy path, which is
    both exact and cheaper at that scale."""

    def __init__(self, kind: str, *, merge_factor: int = DEFAULT_MERGE_FACTOR,
                 max_in_flight: int = DEFAULT_MAX_MAPS, **kwargs):
        self.kind = kind
        self.kwargs = kwargs
        self.merge_factor = max(2, merge_factor)
        self.max_in_flight = max(1, max_in_flight)
        self.stats = ShuffleStats(kind)

    @property
    def name(self) -> str:
        return f"Shuffle({self.kind})"

    # ------------------------------------------------------------- planning
    def _num_partitions(self) -> int:
        if self.kind == "repartition":
            return max(1, self.kwargs["num_blocks"])
        if self.kwargs.get("num_partitions"):
            return max(1, self.kwargs["num_partitions"])
        if self.kind in ("groupby_agg", "map_groups"):
            return default_num_partitions(cap=8)
        return default_num_partitions()

    def _reduce_plan(self, j: int):
        """(reduce_fn, reduce_args) for partition j."""
        k = self.kwargs
        if self.kind == "random_shuffle":
            return exchange.reduce_concat, (
                _derived_seed(self._exec_seed, 1, j),)
        if self.kind == "repartition":
            return exchange.reduce_concat, (None,)
        if self.kind == "sort":
            return exchange.reduce_sorted, (k["key"],
                                            k.get("descending", False))
        if self.kind == "groupby_agg":
            return exchange.reduce_agg, (k["key"], list(k["aggs"]))
        if self.kind == "map_groups":
            return exchange.reduce_map_groups, (k["key"], k["fn"])
        raise ValueError(self.kind)

    def _map_plan(self, n: int, map_idx: int, bounds):
        """(partition_fn, args) for one map task."""
        k = self.kwargs
        if self.kind == "random_shuffle":
            return exchange.partition_random, (
                n, _derived_seed(self._exec_seed, 0, map_idx))
        if self.kind == "repartition":
            return exchange.partition_round_robin, (n,)
        if self.kind == "sort":
            return exchange.partition_range, (
                k["key"], bounds, k.get("descending", False))
        # groupby_agg / map_groups
        return exchange.partition_hash, (k["key"], n)

    # ------------------------------------------------------------ execution
    def execute(self, upstream, budget=None) -> Iterator:
        global _LAST_STATS
        upstream = iter(upstream)
        head = list(itertools.islice(upstream, SMALL_INPUT_BLOCKS + 1))
        if len(head) <= SMALL_INPUT_BLOCKS:
            # tiny input: the barrier is free and the legacy path keeps
            # exact single-block semantics (e.g. one whole-dataset
            # permutation instead of a 2-phase exchange)
            from ray_tpu.data.execution import AllToAllStage
            self.stats.fallback = True
            self.stats.input_blocks = len(head)
            _LAST_STATS = self.stats
            yield from AllToAllStage(self.kind, **self.kwargs).execute(
                iter(head), budget)
            return
        yield from self._stream(itertools.chain(head, upstream), budget)

    def _stream(self, upstream, budget) -> Iterator:
        global _LAST_STATS
        st = self.stats
        _LAST_STATS = st        # visible even if the consumer stops early
        # an unseeded shuffle still permutes within every partition: draw
        # a fresh base seed per execution and derive all task seeds from
        # it (matching the legacy exchange, which always permuted)
        self._exec_seed = self.kwargs.get("seed")
        if self.kind == "random_shuffle" and self._exec_seed is None:
            import numpy as np
            self._exec_seed = int(np.random.default_rng().integers(1 << 31))
        P = self._num_partitions()
        bounds = None
        if self.kind == "sort":
            upstream, bounds = self._sample_bounds(upstream, P)
            P = len(bounds) + 1
        st.num_partitions = P

        map_task = ray_tpu.remote(_shuffle_map).options(num_returns=P + 1)
        merge_task = ray_tpu.remote(_shuffle_merge).options(num_returns=2)

        parts = [_Partition() for _ in range(P)]
        # sizes_ref -> (map_idx, [sub_refs], budget_est)
        inflight: Dict[Any, Tuple[int, List, int]] = {}
        merge_q: collections.deque = collections.deque()  # merge meta refs
        exhausted = False
        map_idx = 0
        peek_est = 0

        # flight-recorder windows: one span for the whole exchange with
        # the ShuffleStats peak-live gauges attached at close, child
        # spans for the map/merge window and the reduce window
        from ray_tpu._private import events
        shuffle_span = events.start_span("data.shuffle", category="data",
                                         kind=self.kind, partitions=P)
        self._rec_span = shuffle_span
        map_span = events.start_span(
            "data.shuffle.map", category="data",
            trace_id=shuffle_span.trace_id,
            parent_span_id=shuffle_span.span_id, kind=self.kind)
        reduce_span = None
        try:
            while True:
                while not exhausted and len(inflight) < self.max_in_flight:
                    est = 0
                    if budget is not None:
                        est = peek_est
                        if not budget.try_acquire(est, force=not inflight):
                            break
                    nxt = next(upstream, None)
                    if nxt is None:
                        if budget is not None:
                            budget.release(est)
                        exhausted = True
                        break
                    ref, meta = nxt
                    peek_est = getattr(meta, "size_bytes", 0) or 0
                    part_fn, args = self._map_plan(P, map_idx, bounds)
                    out = map_task.remote(ref, part_fn, args, P)
                    sub_refs, sizes_ref = list(out[:P]), out[P]
                    inflight[sizes_ref] = (map_idx, sub_refs, est)
                    st.map_tasks += 1
                    st.input_blocks += 1
                    st.input_bytes += peek_est
                    st._touch_inputs(1)
                    map_idx += 1
                    # the input ref is dropped HERE: the map task's arg
                    # holds it until execution; the driver never re-holds it
                    del ref, nxt
                if not inflight:
                    break
                ready, _ = ray_tpu.wait(list(inflight.keys()),
                                        num_returns=1)
                for sizes_ref in ready:
                    idx, sub_refs, est = inflight.pop(sizes_ref)
                    st._touch_inputs(-1)
                    if budget is not None:
                        budget.release(est)
                    sizes = ray_tpu.get(sizes_ref)
                    for j, (sref, (rows, nb)) in enumerate(
                            zip(sub_refs, sizes)):
                        p = parts[j]
                        p.arrived[idx] = (sref, rows, nb)
                        p.rows += rows
                        p.bytes += nb
                        st._touch_partials(1)
                    self._fold_ready_runs(parts, idx, merge_task, merge_q)

            map_span.end(map_tasks=st.map_tasks,
                         merge_tasks=st.merge_tasks,
                         input_blocks=st.input_blocks,
                         input_bytes=st.input_bytes,
                         peak_live_inputs=st.peak_live_inputs)
            reduce_span = events.start_span(
                "data.shuffle.reduce", category="data",
                trace_id=shuffle_span.trace_id,
                parent_span_id=shuffle_span.span_id, kind=self.kind)
            yield from self._reduce_all(parts, P, budget)
            _LAST_STATS = st
        finally:
            map_span.end()      # no-op unless the map window aborted
            if reduce_span is not None:
                reduce_span.end(reduce_tasks=st.reduce_tasks,
                                output_rows=st.output_rows,
                                output_bytes=st.output_bytes,
                                locality_hits=st.locality_hits)
            shuffle_span.end(
                map_tasks=st.map_tasks, merge_tasks=st.merge_tasks,
                reduce_tasks=st.reduce_tasks,
                input_bytes=st.input_bytes, output_bytes=st.output_bytes,
                peak_live_inputs=st.peak_live_inputs,
                peak_live_partials=st.peak_live_partials)
            self._rec_span = None

    def _sample_bounds(self, upstream, P):
        """Buffer a bounded prefix, sample range boundaries from it
        (reference: SortTaskSpec.sample_boundaries). Bounds only steer
        partition BALANCE — any bounds give a correct global order since
        partitions are value-disjoint ranges and each reduce sorts."""
        prefix = []
        for bundle in upstream:
            prefix.append(bundle)
            if len(prefix) >= max(P, 8):
                break
        bounds = exchange.sample_sort_bounds(
            [r for r, _ in prefix], self.kwargs["key"], P)
        return itertools.chain(prefix, upstream), bounds

    def _fold_ready_runs(self, parts, idx, merge_task, merge_q):
        """Launch an intermediate merge in every partition whose run
        [m*F, (m+1)*F) — the FIXED index range containing map ``idx`` —
        has fully arrived. Fixed ranges make the grouping (and therefore
        the final concat order) independent of task completion timing,
        which keeps seeded shuffles reproducible; folding ANY complete
        range (not just the lowest) keeps the driver's live sub-block
        refs bounded even under adversarial completion order."""
        st = self.stats
        F = self.merge_factor
        m = idx // F
        base = m * F
        for p in parts:
            if not all(base + k in p.arrived for k in range(F)):
                continue
            run = [p.arrived.pop(base + k) for k in range(F)]
            nb = sum(r[2] for r in run)
            rows = sum(r[1] for r in run)
            task = merge_task
            node = plurality_node((r[0], r[2]) for r in run)
            if node is not None:
                from ray_tpu.util.scheduling_strategies import \
                    NodeAffinitySchedulingStrategy
                task = merge_task.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node, soft=True))
            block_ref, meta_ref = task.remote(*[r[0] for r in run])
            p.runs[m] = (block_ref, rows, nb)
            st.merge_tasks += 1
            st._touch_partials(-F)
            rec = getattr(self, "_rec_span", None)
            if rec is not None:
                from ray_tpu._private import events
                events.record_instant(
                    "data.shuffle.merge", category="data",
                    trace_id=rec.trace_id, parent_span_id=rec.span_id,
                    run=m, bytes=nb, rows=rows,
                    locality=node is not None)
            merge_q.append(meta_ref)
            # bounded merge pipeline: beyond the cap, wait for the
            # oldest merge before launching more
            while len(merge_q) > DEFAULT_MAX_MERGES:
                ray_tpu.wait([merge_q.popleft()], num_returns=1)

    def _reduce_all(self, parts, P, budget) -> Iterator:
        st = self.stats
        reduce_task = ray_tpu.remote(_shuffle_reduce).options(num_returns=2)
        window = collections.deque()   # (j, block_ref, meta_ref, est)

        def _drain_head():
            j, block_ref, meta_ref, est = window.popleft()
            if budget is not None:
                budget.release(est)
            meta = ray_tpu.get(meta_ref)
            st.output_rows += meta.num_rows
            st.output_bytes += meta.size_bytes
            # empty partitions vanish from the stream — except repartition,
            # whose contract is exactly num_blocks output blocks
            if meta.num_rows or self.kind == "repartition":
                return (block_ref, meta)
            return None

        for j in range(P):
            while len(window) >= DEFAULT_MAX_REDUCES:
                out = _drain_head()
                if out is not None:
                    yield out
            p = parts[j]
            est = p.bytes
            if budget is not None and not budget.try_acquire(
                    est, force=not window):
                # over budget: drain the window head first, then force
                while window:
                    out = _drain_head()
                    if out is not None:
                        yield out
                budget.try_acquire(est, force=True)
            fn, args = self._reduce_plan(j)
            task = reduce_task
            node = plurality_node(p.locality_pairs())
            if node is not None:
                from ray_tpu.util.scheduling_strategies import \
                    NodeAffinitySchedulingStrategy
                task = reduce_task.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node, soft=True))
                st.locality_hits += 1
            refs = p.reduce_refs(self.merge_factor)
            block_ref, meta_ref = task.remote(fn, args, *refs)
            st.reduce_tasks += 1
            st._touch_partials(-len(p.arrived))
            # the partition's run/sub refs are dropped with p: the reduce
            # task's args keep them recoverable through lineage
            parts[j] = None
            window.append((j, block_ref, meta_ref, est))
        while window:
            out = _drain_head()
            if out is not None:
                yield out
