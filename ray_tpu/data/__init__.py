from ray_tpu.data import preprocessors
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.read_api import (from_arrow, from_items, from_numpy,
                                   from_pandas, range, read_csv, read_json,
                                   read_parquet, read_text)

__all__ = ["Dataset", "GroupedData", "range", "from_items", "from_numpy",
           "from_pandas", "from_arrow", "read_parquet", "read_csv",
           "read_json", "read_text", "preprocessors"]
