from ray_tpu.data import preprocessors
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.read_api import (from_arrow, from_huggingface,
                                   from_items, from_numpy, from_pandas,
                                   range, read_bigquery, read_binary_files,
                                   read_csv, read_images, read_json,
                                   read_mongo, read_numpy, read_parquet,
                                   read_sql, read_text, read_tfrecords,
                                   read_webdataset)

__all__ = ["Dataset", "GroupedData", "range", "from_items", "from_numpy",
           "from_pandas", "from_arrow", "from_huggingface", "read_parquet",
           "read_csv", "read_json", "read_text", "read_numpy",
           "read_binary_files", "read_images", "read_tfrecords", "read_sql",
           "read_webdataset", "read_mongo", "read_bigquery",
           "preprocessors"]
