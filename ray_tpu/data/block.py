"""Block model: a Dataset is a list of object-store-resident blocks.

Re-design of the reference's block layer (reference:
python/ray/data/block.py, _internal/arrow_block.py): a block is a pyarrow
Table (columnar, zero-copy through the shm store); batches convert to
"numpy" (dict of arrays), "pandas", or "pyarrow" on demand. TPU-first
consequence: the numpy batch format is the device-feed path
(iterator.iter_jax_batches), so conversions keep arrays contiguous.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional

import numpy as np
import pyarrow as pa


@dataclasses.dataclass
class BlockMetadata:
    num_rows: int
    size_bytes: int
    schema: Optional[Any] = None


def block_from_rows(rows: List[Dict[str, Any]]) -> pa.Table:
    if not rows:
        return pa.table({})
    cols: Dict[str, list] = {}
    for row in rows:
        if not isinstance(row, dict):
            row = {"item": row}
        for k, v in row.items():
            cols.setdefault(k, []).append(v)
    return pa.table({k: _to_arrow_array(v) for k, v in cols.items()})


def _to_arrow_array(values: list):
    first = next((v for v in values if v is not None), None)
    if isinstance(first, np.ndarray):
        # tensor column: fixed-shape list array
        arr = np.stack(values)
        flat = pa.array(arr.reshape(arr.shape[0], -1).tolist())
        return flat
    return pa.array(values)


def block_from_batch(batch) -> pa.Table:
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        return pa.table({k: (pa.array(np.asarray(v).tolist())
                             if isinstance(v, np.ndarray) and v.ndim > 1
                             else pa.array(np.asarray(v)))
                         for k, v in batch.items()})
    try:
        import pandas as pd
        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:
        pass
    if isinstance(batch, list):
        return block_from_rows(batch)
    raise TypeError(f"cannot build a block from {type(batch)}")


def block_to_batch(block: pa.Table, batch_format: str = "numpy"):
    if batch_format == "pyarrow":
        return block
    if batch_format == "pandas":
        return block.to_pandas()
    if batch_format in ("numpy", "default"):
        return {name: np.asarray(block.column(name).to_numpy(
            zero_copy_only=False)) for name in block.column_names}
    raise ValueError(f"unknown batch_format {batch_format!r}")


def block_to_rows(block: pa.Table) -> Iterable[Dict[str, Any]]:
    cols = {name: block.column(name).to_pylist()
            for name in block.column_names}
    for i in range(block.num_rows):
        yield {k: v[i] for k, v in cols.items()}


def block_metadata(block: pa.Table) -> BlockMetadata:
    return BlockMetadata(num_rows=block.num_rows,
                         size_bytes=block.nbytes,
                         schema=block.schema)


def slice_block(block: pa.Table, start: int, end: int) -> pa.Table:
    return block.slice(start, end - start)


def concat_blocks(blocks: List[pa.Table]) -> pa.Table:
    nonempty = [b for b in blocks if b.num_rows > 0]
    if not nonempty:
        # preserve the schema through an all-empty concat: a shuffle
        # partition that received only empty sub-blocks must still carry
        # its columns (downstream schema() / writes depend on it)
        for b in blocks:
            if len(b.column_names):
                return b.slice(0, 0)
        return pa.table({})
    return pa.concat_tables(nonempty, promote_options="default")
