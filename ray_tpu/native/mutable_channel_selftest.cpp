// Standalone ASan/UBSan harness for the mutable shared-memory channel
// (the compiled-graph channel substrate) — companion to
// shm_store_selftest.cpp; built by native/build.py build_selftest and
// run as a subprocess by tests/test_sanitizers.py.
//
// Exercises: writer/reader version-gated handoff over many rounds with
// 2 reader threads on separate opens (cross-mapping coherence), payload
// integrity per version, write_acquire back-pressure until every reader
// acks, timeout paths, and closed-channel propagation.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* rtc_create(const char* path, uint64_t max_size, uint32_t num_readers);
void* rtc_open(const char* path);
void rtc_close(void* hc);
uint8_t* rtc_payload(void* hc);
uint64_t rtc_max_size(void* hc);
int rtc_write_acquire(void* hc, int64_t timeout_ms);
int rtc_write_publish(void* hc, uint64_t data_size);
int64_t rtc_read_acquire(void* hc, uint64_t last_version,
                         int64_t timeout_ms, uint64_t* data_size);
int rtc_read_release(void* hc, uint64_t version);
int rtc_set_closed(void* hc);
uint64_t rtc_version(void* hc);
}

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,         \
              __LINE__, #cond);                                        \
      exit(1);                                                         \
    }                                                                  \
  } while (0)

static constexpr int kRounds = 200;
static constexpr uint64_t kPayload = 4096;

int main(int argc, char** argv) {
  std::string path = argc > 1 ? argv[1] : "/dev/shm/rtc_selftest";
  void* w = rtc_create(path.c_str(), kPayload, 2);
  CHECK(w != nullptr);
  CHECK(rtc_max_size(w) == kPayload);

  // read timeout on an empty channel
  uint64_t dsz = 0;
  void* probe = rtc_open(path.c_str());
  CHECK(probe != nullptr);
  CHECK(rtc_read_acquire(probe, 0, 50, &dsz) == 0);  // timeout
  rtc_close(probe);

  std::atomic<int> failures{0};
  auto reader = [&](int rid) {
    void* r = rtc_open(path.c_str());
    if (!r) { failures++; return; }
    uint8_t* buf = rtc_payload(r);
    uint64_t last = 0;
    for (;;) {
      uint64_t sz = 0;
      int64_t v = rtc_read_acquire(r, last, 5000, &sz);
      if (v == -2) break;          // closed and drained
      if (v <= 0) { failures++; break; }
      // payload integrity: every byte stamps the version
      if (sz != kPayload) failures++;
      for (uint64_t k = 0; k < sz; k += 97)
        if (buf[k] != (uint8_t)(v & 0xff)) { failures++; break; }
      rtc_read_release(r, (uint64_t)v);
      last = (uint64_t)v;
    }
    rtc_close(r);
  };

  std::thread t1(reader, 1), t2(reader, 2);

  uint8_t* wbuf = rtc_payload(w);
  for (int round = 1; round <= kRounds; round++) {
    CHECK(rtc_write_acquire(w, 5000) == 0);  // waits for both acks
    memset(wbuf, round & 0xff, kPayload);
    CHECK(rtc_write_publish(w, kPayload) == 0);
  }
  // wait until the final version is fully acked, then close
  CHECK(rtc_write_acquire(w, 5000) == 0);
  CHECK(rtc_version(w) == (uint64_t)kRounds);
  CHECK(rtc_set_closed(w) == 0);
  t1.join();
  t2.join();
  CHECK(failures.load() == 0);

  // writes on a closed channel fail
  CHECK(rtc_write_acquire(w, 100) == -2);
  rtc_close(w);
  remove(path.c_str());
  printf("mutable_channel_selftest: OK\n");
  return 0;
}
