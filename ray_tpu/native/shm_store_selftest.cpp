// Standalone ASan/UBSan harness for the shm object store — the
// build:asan/build:tsan analog for this repo's native layer (reference:
// .bazelrc build:asan + src/ray/object_manager plasma store tests run
// under sanitizers in CI). Built by native/build.py with
// -fsanitize=address,undefined and run as a subprocess by
// tests/test_sanitizers.py; any heap-buffer-overflow / UB aborts the
// process with a nonzero exit.
//
// Exercises: create/seal/get/release/delete round trips, abort of
// unsealed objects, LRU eviction under pressure, cross-handle open, and
// multi-threaded hammering of one arena (the robust-mutex path).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* rt_store_create(const char* path, uint64_t size);
void* rt_store_open(const char* path);
void rt_store_close(void* hs);
uint8_t* rt_store_base(void* hs);
int64_t rt_create(void* hs, const uint8_t* id, uint64_t data_size,
                  uint64_t meta_size, int evictable);
int rt_seal(void* hs, const uint8_t* id);
int64_t rt_get(void* hs, const uint8_t* id, uint64_t* data_size,
               uint64_t* meta_size, int pin);
int rt_release(void* hs, const uint8_t* id);
int rt_contains(void* hs, const uint8_t* id);
int rt_delete(void* hs, const uint8_t* id);
int rt_abort(void* hs, const uint8_t* id);
uint64_t rt_evict(void* hs, uint64_t bytes);
void rt_stats(void* hs, uint64_t* out);
void rt_write_parallel(void* dst, const void* src, uint64_t n, int threads);
}

static constexpr int kIdLen = 20;

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,         \
              __LINE__, #cond);                                        \
      return 1;                                                        \
    }                                                                  \
  } while (0)

static void make_id(uint8_t* id, uint64_t n) {
  memset(id, 0, kIdLen);
  memcpy(id, &n, sizeof(n));
}

int main(int argc, char** argv) {
  std::string path = argc > 1 ? argv[1] : "/dev/shm/rt_selftest";
  const uint64_t kArena = 4 << 20;  // 4 MiB
  void* s = rt_store_create(path.c_str(), kArena);
  CHECK(s != nullptr);

  // --- round trip -------------------------------------------------------
  uint8_t id[kIdLen];
  make_id(id, 1);
  int64_t off = rt_create(s, id, 1024, 16, 1);
  CHECK(off > 0);
  uint8_t* base = rt_store_base(s);
  memset(base + off, 0xAB, 1024 + 16);  // fill data+meta exactly
  CHECK(rt_seal(s, id) == 0);
  uint64_t dsz = 0, msz = 0;
  int64_t goff = rt_get(s, id, &dsz, &msz, 1);
  CHECK(goff == off && dsz == 1024 && msz == 16);
  for (int i = 0; i < 1024; i++) CHECK(base[goff + i] == 0xAB);
  CHECK(rt_release(s, id) == 0);
  CHECK(rt_contains(s, id) == 1);

  // --- abort of an unsealed object -------------------------------------
  uint8_t id2[kIdLen];
  make_id(id2, 2);
  CHECK(rt_create(s, id2, 256, 0, 1) > 0);
  CHECK(rt_abort(s, id2) == 0);
  CHECK(rt_contains(s, id2) == 0);

  // --- delete-pending while pinned --------------------------------------
  make_id(id2, 3);
  CHECK(rt_create(s, id2, 128, 0, 1) > 0);
  CHECK(rt_seal(s, id2) == 0);
  CHECK(rt_get(s, id2, &dsz, &msz, 1) > 0);
  CHECK(rt_delete(s, id2) == 0);       // pinned: becomes delete-pending
  CHECK(rt_release(s, id2) == 0);      // release completes the delete
  CHECK(rt_contains(s, id2) == 0);

  // --- eviction under pressure ------------------------------------------
  // fill beyond capacity with 64 KiB objects; creates must keep
  // succeeding via LRU eviction of sealed, unpinned entries
  for (uint64_t n = 100; n < 100 + 128; n++) {
    uint8_t eid[kIdLen];
    make_id(eid, n);
    int64_t o = rt_create(s, eid, 64 << 10, 0, 1);
    CHECK(o > 0);
    memset(base + o, (int)(n & 0xff), 64 << 10);
    CHECK(rt_seal(s, eid) == 0);
  }
  uint64_t st[9];
  rt_stats(s, st);
  CHECK(st[3] > 0);       // evictions happened
  CHECK(st[8] == 0);      // not poisoned

  // --- cross-handle open -------------------------------------------------
  void* s2 = rt_store_open(path.c_str());
  CHECK(s2 != nullptr);
  CHECK(rt_contains(s2, id) == rt_contains(s, id));

  // --- concurrent hammering ---------------------------------------------
  std::atomic<int> failures{0};
  auto worker = [&](int tid) {
    void* h = rt_store_open(path.c_str());
    if (!h) { failures++; return; }
    uint8_t* b = rt_store_base(h);
    for (uint64_t n = 0; n < 200; n++) {
      uint8_t wid[kIdLen];
      make_id(wid, 10000 + tid * 1000 + n);
      int64_t o = rt_create(h, wid, 4096, 0, 1);
      if (o <= 0) continue;  // ENOMEM under pressure is legal
      memset(b + o, tid, 4096);
      if (rt_seal(h, wid) != 0) { failures++; continue; }
      uint64_t d, m;
      int64_t g = rt_get(h, wid, &d, &m, 1);
      if (g > 0) {
        if (b[g] != (uint8_t)tid || d != 4096) failures++;
        rt_release(h, wid);
      }
      if (n % 3 == 0) rt_delete(h, wid);
    }
    rt_store_close(h);
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; t++) ts.emplace_back(worker, t);
  for (auto& t : ts) t.join();
  CHECK(failures.load() == 0);

  rt_stats(s, st);
  CHECK(st[8] == 0);

  // --- parallel chunked copies (the off-loop put data path) --------------
  // correctness across split shapes (1 thread = plain memcpy; >1 exercises
  // the pool, odd sizes exercise the tail chunk), then 4 caller threads
  // hammering rt_write_parallel concurrently INTO the arena while others
  // create/seal — the data race surface the tsan wiring exists to watch.
  {
    const uint64_t kN = (3 << 20) + 137;  // odd size: tail chunk
    std::vector<uint8_t> src(kN), dst(kN);
    for (uint64_t i = 0; i < kN; i++) src[i] = (uint8_t)(i * 31 + 7);
    for (int threads : {1, 2, 4, 7}) {
      memset(dst.data(), 0, kN);
      rt_write_parallel(dst.data(), src.data(), kN, threads);
      CHECK(memcmp(dst.data(), src.data(), kN) == 0);
    }

    // payloads above the 1 MiB split threshold so concurrent callers
    // genuinely share the pool (queue + per-batch completion handshake);
    // a separate 32 MiB arena keeps this from thrashing the tiny store
    // the eviction section above sized deliberately small
    std::string cpath = path + ".copy";
    void* cs = rt_store_create(cpath.c_str(), 32 << 20);
    CHECK(cs != nullptr);
    std::atomic<int> copy_failures{0};
    auto copier = [&](int tid) {
      void* h = rt_store_open(cpath.c_str());
      if (!h) { copy_failures++; return; }
      uint8_t* b = rt_store_base(h);
      std::vector<uint8_t> payload((3 << 20) + 64 * tid);
      for (size_t i = 0; i < payload.size(); i++)
        payload[i] = (uint8_t)(tid * 13 + i);
      for (uint64_t n = 0; n < 20; n++) {
        uint8_t wid[kIdLen];
        make_id(wid, 50000 + tid * 1000 + n);
        int64_t o = rt_create(h, wid, payload.size(), 0, 1);
        if (o <= 0) continue;  // ENOMEM under pressure is legal
        rt_write_parallel(b + o, payload.data(), payload.size(), 4);
        if (rt_seal(h, wid) != 0) { copy_failures++; continue; }
        uint64_t d, m;
        int64_t g = rt_get(h, wid, &d, &m, 1);
        if (g > 0) {
          if (memcmp(b + g, payload.data(), payload.size()) != 0)
            copy_failures++;
          rt_release(h, wid);
        }
        rt_delete(h, wid);
      }
      rt_store_close(h);
    };
    std::vector<std::thread> cts;
    for (int t = 0; t < 4; t++) cts.emplace_back(copier, t);
    for (auto& t : cts) t.join();
    CHECK(copy_failures.load() == 0);
    rt_store_close(cs);
    remove(cpath.c_str());
  }

  rt_stats(s, st);
  CHECK(st[8] == 0);
  rt_store_close(s2);
  rt_store_close(s);
  remove(path.c_str());
  printf("shm_store_selftest: OK\n");
  return 0;
}
